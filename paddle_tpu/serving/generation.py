"""Continuous-batching generation server over the paged KV-cache.

The static decode loop (models/transformer.build_lm_kv_decoder) serves
a CLOSED batch: everyone starts together, nobody leaves until the last
sequence finishes, and a new request waits for the whole batch to
drain.  `GenerationServer` replaces that with the vLLM-style in-flight
schedule:

* ONE resident decode step (build_lm_paged_decoder) runs per tick over
  the active slot set — a single device dispatch per token position;
* BETWEEN ticks the scheduler admits queued requests into free slots
  (prefill is folded into the same per-token step: a just-admitted
  sequence is teacher-forced through its prompt positions while
  everyone else decodes), evicts finished sequences IMMEDIATELY and
  returns their KV blocks to the pool;
* admission is keyed to free KV blocks (a request is admitted only
  when its whole prompt+max_new budget fits, so decode can never hit
  an out-of-pool condition mid-sequence), queued requests past their
  deadline are shed at dequeue, and a full queue rejects with
  ServerSaturated at submit;
* every request streams tokens through its own `GenerationStream`
  future, and per-request numerics are bit-identical to running the
  same prompt alone (slot math is independent of batch composition —
  tests/test_generation_serving.py pins this);
* `swap_states` performs the zero-downtime checkpoint hot swap: stop
  admitting, let active sequences drain, swap parameters, resume —
  queued requests wait instead of failing.

`static_batch=True` degrades the scheduler to the drain-then-refill
baseline (admit only into an EMPTY active set) — same compiled step,
same numerics — which is what benchmark/run_serving.py measures the
continuous schedule against.

On top of the PR 8 substrate ride the two algorithmic serving
optimizations (docs/serving.md):

* PREFIX CACHING (`prefix_cache=True`, default): admission allocates
  through `PagedKVCache.allocate_prefix`, which shares fully-filled
  prompt blocks already resident from an earlier sequence with the
  same prompt prefix — the cursor then STARTS past the shared
  positions, skipping their prefill ticks entirely.  K/V at a position
  is a deterministic function of the token prefix, so shared blocks
  hold exactly what this sequence's prefill would have written:
  greedy output stays bit-identical to a cold run.
* SPECULATIVE DECODING (`draft_decoder`/`draft_states`, optional): a
  small draft model proposes `spec_k` greedy tokens per tick and the
  target verifies the whole window in ONE `step_window` dispatch.  The
  accept rule is the greedy degenerate of accept/resample — keep
  proposals while they equal the target's own argmax chain, then emit
  the target's next token as the bonus — so the emitted stream is the
  target's greedy output BY CONSTRUCTION; a tick delivers between 1
  and spec_k+1 tokens.  The draft keeps its own KV pool indexed by the
  SAME block tables (admission accounts blocks once; prefix hits warm
  both pools).  Sampled (temperature>0) requests take the plain
  one-token path — their per-(seed, position) PRNG contract is
  untouched.  Prefill is chunked through the same window step
  (spec_k+1 prompt positions per tick) when a draft is armed.
"""
from __future__ import annotations

import itertools
import json
import os
import threading
import time
import warnings
from collections import deque
from typing import Dict, List, Optional, Sequence

import numpy as np

from ..core.resilience import (fault_injector,
                               sched_fault_armed as _sched_fault)
from ..observability import attribution as obs_attr
from ..observability import metrics as obs_metrics
from ..observability import tracing as obs_tracing
from .batching import RequestDeadlineExceeded, ServerSaturated
from .kv_cache import KVPoolExhausted, PagedKVCache

__all__ = ["GenerationServer", "GenerationStream",
           "save_generation_model", "load_generation_model",
           "build_warm_start_artifact"]

MODEL_SPEC_FILENAME = "generation.json"
MODEL_PARAMS_FILENAME = "generation_params.npz"
MODEL_DRAFT_PARAMS_FILENAME = "generation_draft_params.npz"
# the warm-start artifact: a persistent XLA compilation cache shipped
# NEXT TO the model (save_generation_model(warm_start=True) /
# build_warm_start_artifact).  A scale-out replica started from the dir
# points PADDLE_TPU_COMPILATION_CACHE_DIR at it and DESERIALIZES the
# serving executables instead of compiling them, so its time-to-first-
# token is bounded by model load, not XLA compile (docs/serving.md
# "Autoscaling").
WARM_START_DIRNAME = "xla_cache"

_SERVER_IDS = itertools.count()
# stats()-backing series are always=True (the stats contract predates
# the PADDLE_TPU_METRICS switch); latency/depth series are gated.
_M_REQUESTS = obs_metrics.counter(
    "paddle_tpu_serving_generation_requests_total",
    "generation requests admitted to a decode slot", ("server",),
    always=True)
_M_TOKENS = obs_metrics.counter(
    "paddle_tpu_serving_generated_tokens_total",
    "generated tokens delivered to request streams", ("server",),
    always=True)
_M_TICKS = obs_metrics.counter(
    "paddle_tpu_serving_decode_ticks_total",
    "resident decode steps dispatched (tokens/tick = active slots)",
    ("server",), always=True)
_M_SHED = obs_metrics.counter(
    "paddle_tpu_serving_generation_shed_total",
    "requests shed instead of decoded, by reason "
    "(saturated: full queue at submit; deadline: expired while queued)",
    ("server", "reason"), always=True)
_M_SWAPS = obs_metrics.counter(
    "paddle_tpu_serving_hot_swaps_total",
    "zero-downtime checkpoint hot swaps completed", ("server",),
    always=True)
_M_LATENCY = obs_metrics.histogram(
    "paddle_tpu_serving_generation_seconds",
    "submit -> last-token wall latency per request", ("server",))
_M_TTFT = obs_metrics.histogram(
    "paddle_tpu_serving_first_token_seconds",
    "submit -> first generated token wall latency", ("server",))
_M_ACTIVE = obs_metrics.gauge(
    "paddle_tpu_serving_active_sequences",
    "sequences currently holding a decode slot", ("server",))
_M_QDEPTH = obs_metrics.gauge(
    "paddle_tpu_serving_generation_queue_depth",
    "requests waiting for admission", ("server",))
_M_DRAFT_PROPOSED = obs_metrics.counter(
    "paddle_tpu_serving_draft_proposed_total",
    "draft-model tokens proposed for target verification "
    "(speculative decoding)", ("server",), always=True)
_M_DRAFT_ACCEPTED = obs_metrics.counter(
    "paddle_tpu_serving_draft_accepted_total",
    "draft proposals accepted by the target's verify step "
    "(accept rate = accepted / proposed)", ("server",), always=True)


class GenerationStream:
    """Per-request streaming future: tokens arrive as the scheduler
    delivers them; `result()` blocks for the full generation.

    for tok in stream:            # streams tokens as they are decoded
        ...
    ids = stream.result()         # or: block until finished

    A failed request raises from both paths; a shed request raises the
    shed error (RequestDeadlineExceeded)."""

    def __init__(self, prompt: Sequence[int], max_new: int):
        self.prompt = [int(t) for t in prompt]
        self.max_new = int(max_new)
        self._cond = threading.Condition()
        self._tokens: List[int] = []
        self._done = False
        self._exc: Optional[BaseException] = None
        self._watchers = 0

    # -- scheduler side -----------------------------------------------------
    def _put(self, tok: int):
        with self._cond:
            self._tokens.append(int(tok))
            # wake waiters per token only when a live iterator streams
            # this request; result()-style waiters block on `done` and
            # a wakeup per token is pure GIL churn on the decode path
            # (it measurably dilutes the continuous-batching win)
            if self._watchers:
                self._cond.notify_all()

    def _finish(self):
        with self._cond:
            self._done = True
            self._cond.notify_all()

    def _fail(self, exc: BaseException):
        with self._cond:
            if not self._done:
                self._exc = exc
                self._done = True
                self._cond.notify_all()

    # -- client side --------------------------------------------------------
    @property
    def done(self) -> bool:
        with self._cond:
            return self._done

    def tokens_so_far(self) -> List[int]:
        with self._cond:
            return list(self._tokens)

    def __iter__(self):
        if _sched_fault("stream.yield-under-lock"):
            # the pre-PR-8 bug, reintroducible ONLY for the schedule
            # checker's regression pin (tests/test_concurrency_
            # analysis.py): yielding with the lock held lets a slow
            # consumer stall the scheduler's _put
            yield from self._iter_yield_under_lock()
            return
        i = 0
        with self._cond:
            self._watchers += 1
        try:
            while True:
                # snapshot under the lock, yield OUTSIDE it: a consumer
                # that processes tokens slowly (a replica writing to a
                # slow TCP client) must never block the scheduler's
                # _put — that would stall every other request's decode
                with self._cond:
                    self._cond.wait_for(
                        lambda: self._done or len(self._tokens) > i)
                    batch = self._tokens[i:]
                    done = self._done  # final: no tokens arrive after
                    exc = self._exc
                for tok in batch:
                    yield tok
                i += len(batch)
                if done:
                    if exc is not None:
                        raise exc
                    return
        finally:
            with self._cond:
                self._watchers -= 1

    def _iter_yield_under_lock(self):
        i = 0
        with self._cond:
            self._watchers += 1
            try:
                while True:
                    self._cond.wait_for(
                        lambda: self._done or len(self._tokens) > i)
                    while i < len(self._tokens):
                        yield self._tokens[i]   # lock HELD across yield
                        i += 1
                    if self._done:
                        if self._exc is not None:
                            raise self._exc
                        return
            finally:
                self._watchers -= 1

    def result(self, timeout: Optional[float] = None) -> List[int]:
        with self._cond:
            if not self._cond.wait_for(lambda: self._done, timeout):
                raise TimeoutError("generation still running")
            if self._exc is not None:
                raise self._exc
            return list(self._tokens)


class _Seq:
    """Scheduler-internal state of one admitted request."""

    __slots__ = ("stream", "tokens", "prompt_len", "max_new", "eos_id",
                 "temperature", "seed", "cur", "slot", "emitted",
                 "t_submit", "t_submit_wall", "expires", "trace_ctx",
                 "draft_next", "prompt_keys")

    def __init__(self, stream, max_new, eos_id, temperature, seed,
                 expires, trace_ctx):
        self.stream = stream
        self.tokens = list(stream.prompt)
        self.prompt_len = len(stream.prompt)
        self.max_new = int(max_new)
        self.eos_id = eos_id
        self.temperature = float(temperature)
        self.seed = int(seed) & 0xFFFFFFFF
        self.cur = 0
        self.slot = -1
        self.emitted = 0
        self.t_submit = time.perf_counter()
        self.t_submit_wall = time.time()
        self.expires = expires
        self.trace_ctx = trace_ctx
        # next position the DRAFT model's KV is missing (speculative
        # decoding: the draft trails the target by at most one
        # position after a fully-accepted window)
        self.draft_next = 0
        # chained prefix-cache block keys, computed ONCE at submit
        # (the scheduler re-checks a blocked queue head every tick)
        self.prompt_keys = None

    @property
    def positions_needed(self) -> int:
        # the cursor writes K/V at positions 0 .. prompt+max_new-2 (the
        # final emitted token is delivered, never re-attended)
        return self.prompt_len + self.max_new - 1


class GenerationServer:
    """Continuous-batching decode scheduler over one paged decoder.

    decoder/states: a models/transformer.build_lm_paged_decoder bundle
    plus its trained parameter dict (names must match
    decoder.state_names — same unique-name discipline as the other
    generator builders).  `slots` bounds concurrent sequences,
    `kv_blocks` is the preallocated pool budget shared by ALL of them.
    """

    def __init__(self, decoder, states, *, slots: int = 8,
                 kv_blocks: int = 64, max_queue: int = 256,
                 place=None, static_batch: bool = False,
                 idle_poll_s: float = 0.005,
                 prefix_cache: bool = True,
                 draft_decoder=None, draft_states=None,
                 spec_k: Optional[int] = None):
        import jax

        from ..core import flags as core_flags
        from ..core.executor import TPUPlace

        def _check_states(dec, sts, who):
            missing = [n for n in dec.state_names if n not in sts]
            if missing:
                raise ValueError(
                    f"{who} states missing {len(missing)} decoder "
                    f"parameter(s), e.g. {missing[:3]} — rebuild the "
                    "decoder under the same unique-name state the "
                    "parameters were trained in")
            # matching NAMES are not enough: a spec that rebuilds the
            # decoder at the wrong max_len/d_model would index the
            # position table out of bounds inside jit, where gathers
            # CLAMP — silently wrong tokens instead of an error.
            bad = [(n, tuple(np.shape(sts[n])), want)
                   for n, want in getattr(dec, "state_shapes",
                                          {}).items()
                   if tuple(np.shape(sts[n])) != want]
            if bad:
                n, got, want = bad[0]
                raise ValueError(
                    f"{len(bad)} {who} parameter shape(s) do not match "
                    f"the decoder architecture, e.g. {n}: states {got} "
                    f"vs decoder {want} — the model spec (vocab_size/"
                    "d_model/n_heads/n_layers/block_size*"
                    "max_blocks_per_seq) disagrees with the saved "
                    "parameters")

        _check_states(decoder, states, "target")
        if (draft_decoder is None) != (draft_states is None):
            raise ValueError(
                "speculative decoding needs BOTH draft_decoder and "
                "draft_states (or neither)")
        if draft_decoder is not None:
            _check_states(draft_decoder, draft_states, "draft")
            if (draft_decoder.block_size != decoder.block_size
                    or draft_decoder.max_blocks_per_seq
                    != decoder.max_blocks_per_seq):
                raise ValueError(
                    "draft decoder block geometry "
                    f"({draft_decoder.block_size}x"
                    f"{draft_decoder.max_blocks_per_seq}) must match "
                    f"the target ({decoder.block_size}x"
                    f"{decoder.max_blocks_per_seq}) — both pools are "
                    "indexed by the SAME per-sequence block tables")
            if draft_decoder.vocab_size != decoder.vocab_size:
                raise ValueError("draft/target vocab_size mismatch")
        self._decoder = decoder
        self._draft = draft_decoder
        self._spec_k = int(spec_k
                           if spec_k is not None
                           else core_flags.get_flag("serving_spec_k"))
        if draft_decoder is not None and self._spec_k < 1:
            raise ValueError("spec_k must be >= 1 with a draft model")
        self._slots = int(slots)
        self._static = bool(static_batch)
        self._idle_poll_s = float(idle_poll_s)
        place = place or TPUPlace()
        self._device = place.jax_device()
        self._states = {n: jax.device_put(np.asarray(states[n]),
                                          self._device)
                        for n in decoder.state_names}
        sid = self._sid = str(next(_SERVER_IDS))
        bpb = getattr(decoder, "bytes_per_block", 0)
        if draft_decoder is not None:
            bpb += getattr(draft_decoder, "bytes_per_block", 0)
        self._cache = PagedKVCache(
            kv_blocks, decoder.block_size, decoder.max_blocks_per_seq,
            server_label=f"gen{sid}", prefix_cache=prefix_cache,
            bytes_per_block=bpb)
        # int8 pools cannot share a prompt's FINAL block: the
        # block-aligned full-prompt hit re-runs the last prompt
        # position, and an int8 write RE-QUANTIZES the whole shared
        # block in place — mutating bytes other live sequences attend
        # to.  fp32 and bf16 writes touch only their own (block,
        # offset) slot with byte-identical values (decode is
        # deterministic in the prefix), so they keep full sharing;
        # for int8 the submit-time keys drop the last prompt token,
        # which excludes exactly the aligned final block.
        self._kv_int8 = (
            getattr(decoder, "kv_dtype", "fp32") == "int8"
            or (draft_decoder is not None
                and getattr(draft_decoder, "kv_dtype", "fp32")
                == "int8"))
        # +1: device block 0 is the reserved null/scratch block
        self._pool_k, self._pool_v = decoder.init_pool(
            kv_blocks + 1, self._device)
        if draft_decoder is not None:
            self._draft_states = {
                n: jax.device_put(np.asarray(draft_states[n]),
                                  self._device)
                for n in draft_decoder.state_names}
            self._dpool_k, self._dpool_v = draft_decoder.init_pool(
                kv_blocks + 1, self._device)

        self._active: List[Optional[_Seq]] = [None] * self._slots
        self._tables = np.zeros(
            (self._slots, decoder.max_blocks_per_seq), np.int32)
        self._queue: deque = deque()
        self._max_queue = int(max_queue)
        self._lock = threading.Condition()
        self._stop = False
        self._draining = False
        self._pending_states = None
        self._swap_done = threading.Event()
        # which warm-start artifact (if any) fed this server's warmup;
        # server_from_model_dir sets it for ping/stats introspection
        self.warm_start_dir: Optional[str] = None

        self._m_requests = _M_REQUESTS.labels(server=sid)
        self._m_tokens = _M_TOKENS.labels(server=sid)
        self._m_ticks = _M_TICKS.labels(server=sid)
        self._m_shed = _M_SHED.labels(server=sid, reason="saturated")
        self._m_deadline = _M_SHED.labels(server=sid, reason="deadline")
        self._m_swaps = _M_SWAPS.labels(server=sid)
        self._m_latency = _M_LATENCY.labels(server=sid)
        self._m_ttft = _M_TTFT.labels(server=sid)
        self._m_active = _M_ACTIVE.labels(server=sid)
        self._m_qdepth = _M_QDEPTH.labels(server=sid)
        self._m_proposed = _M_DRAFT_PROPOSED.labels(server=sid)
        self._m_accepted = _M_DRAFT_ACCEPTED.labels(server=sid)

        from ..core.executor import xla_compile_counts

        c0 = xla_compile_counts()
        t0 = time.perf_counter()
        self._warmup()
        c1 = xla_compile_counts()
        # warm-start accounting (process-wide counters, diffed around
        # THIS warmup): cache_misses == 0 with hits > 0 means every
        # serving executable deserialized from a warm-start artifact —
        # the cold-start contract ROADMAP 4's autoscaler relies on
        self.warmup_stats = {
            "warmup_s": round(time.perf_counter() - t0, 4),
            "compiles": int(c1["compiles"] - c0["compiles"]),
            "compile_seconds": round(
                c1["compile_seconds"] - c0["compile_seconds"], 4),
            "cache_hits": int(c1["cache_hits"] - c0["cache_hits"]),
            "cache_misses": int(c1["cache_misses"]
                                - c0["cache_misses"]),
        }
        self._compiles_after_warmup_base = int(c1["compiles"])
        self._worker = threading.Thread(target=self._loop, daemon=True)
        self._worker.start()

    def _warmup(self):
        """Compile the resident step(s) before the first request:
        serving never pays the trace+compile inside a request's
        latency.  A speculative server compiles the target's window
        step plus the draft's window and single steps; a plain server
        compiles only the one-token step it runs."""
        z = np.zeros(self._slots, np.int32)
        zs = z.astype(np.uint32)
        zt = np.zeros(self._slots, np.float32)
        if self._draft is None:
            nxt, self._pool_k, self._pool_v = self._decoder.step(
                self._states, self._pool_k, self._pool_v, self._tables,
                z, z, zs, zt, np.zeros(self._slots, bool))
            np.asarray(nxt)  # block: compile is done when this returns
            return
        w = self._spec_k + 1
        zw = np.zeros((self._slots, w), np.int32)
        nxt, self._pool_k, self._pool_v = self._decoder.step_window(
            self._states, self._pool_k, self._pool_v, self._tables,
            z, zw, zs, zt, z)
        np.asarray(nxt)
        nxt, self._dpool_k, self._dpool_v = self._draft.step_window(
            self._draft_states, self._dpool_k, self._dpool_v,
            self._tables, z, zw, zs, zt, z)
        np.asarray(nxt)
        nxt, self._dpool_k, self._dpool_v = self._draft.step(
            self._draft_states, self._dpool_k, self._dpool_v,
            self._tables, z, z, zs, zt,
            np.zeros(self._slots, bool))
        np.asarray(nxt)

    # -- client side --------------------------------------------------------
    def submit(self, prompt_ids, max_new_tokens: int, *,
               temperature: float = 0.0, seed: int = 0,
               eos_id: Optional[int] = None,
               deadline_ms: Optional[float] = None) -> GenerationStream:
        """Enqueue one generation request; returns its token stream.

        Requests whose prompt+max_new budget can never fit a sequence's
        block-table capacity are rejected with ValueError up front; a
        full admission queue raises ServerSaturated (backpressure); a
        request still queued when `deadline_ms` passes is shed with
        RequestDeadlineExceeded instead of occupying a slot."""
        prompt = [int(t) for t in np.asarray(prompt_ids).reshape(-1)]
        if not prompt:
            raise ValueError("empty prompt")
        if max_new_tokens < 1:
            raise ValueError("max_new_tokens must be >= 1")
        stream = GenerationStream(prompt, max_new_tokens)
        expires = (time.monotonic() + deadline_ms / 1000.0
                   if deadline_ms is not None else None)
        seq = _Seq(stream, max_new_tokens, eos_id, temperature, seed,
                   expires, obs_tracing.current_context())
        need = self._cache.blocks_for(seq.positions_needed)
        if (need > self._cache.max_blocks_per_seq
                or seq.positions_needed > self._decoder.max_len):
            raise ValueError(
                f"prompt {len(prompt)} + max_new {max_new_tokens} "
                f"needs {need} KV blocks > per-sequence capacity "
                f"{self._cache.max_blocks_per_seq} "
                f"(block_size {self._cache.block_size})")
        if self._cache.prefix_cache:
            keyed = (prompt[:-1] if self._kv_int8 else prompt)
            seq.prompt_keys = self._cache.prompt_keys(keyed)
        with self._lock:
            if self._stop:
                raise RuntimeError("GenerationServer is closed")
            if self._draining:
                # retryable by contract: the replica front maps
                # RuntimeError to a non-fatal wire error, so a router
                # resubmits on a survivor — a draining replica sheds
                # ADMISSION, never an accepted request
                raise RuntimeError(
                    "GenerationServer is draining (graceful scale-in/"
                    "shutdown): submit on another replica")
            if len(self._queue) >= self._max_queue:
                self._m_shed.inc()
                raise ServerSaturated(
                    f"GenerationServer queue full ({self._max_queue} "
                    "pending) — backpressure: retry later or raise "
                    "max_queue")
            self._queue.append(seq)
            self._lock.notify_all()
        if obs_metrics.enabled():
            self._m_qdepth.set(len(self._queue))
        return stream

    def generate(self, prompt_ids, max_new_tokens: int,
                 timeout: Optional[float] = None, **kw) -> List[int]:
        """Synchronous convenience wrapper around submit()."""
        return self.submit(prompt_ids, max_new_tokens, **kw).result(
            timeout)

    def swap_states(self, states: Dict[str, np.ndarray],
                    draft_states: Optional[Dict] = None,
                    wait: bool = True,
                    timeout: Optional[float] = None) -> bool:
        """Zero-downtime checkpoint hot swap: drain -> swap -> resume.

        Admission pauses, active sequences run to completion against
        the OLD parameters (a generation never mixes checkpoints),
        then the new parameters are installed and admission resumes.
        Queued requests are NOT failed — they wait out the drain.

        With a draft armed, pass the new checkpoint's `draft_states`
        too: a stale draft stays CORRECT (the target verifies every
        window) but its accept rate against the new target can
        collapse toward 1/vocab — a silent throughput regression.
        Omitting them keeps the old draft."""
        missing = [n for n in self._decoder.state_names
                   if n not in states]
        if missing:
            raise ValueError(f"swap states missing {missing[:3]}...")
        new_draft = None
        if draft_states is not None:
            if self._draft is None:
                raise ValueError(
                    "draft_states given but this server has no draft "
                    "armed (a draft cannot be armed mid-flight)")
            dmissing = [n for n in self._draft.state_names
                        if n not in draft_states]
            if dmissing:
                raise ValueError(
                    f"swap draft states missing {dmissing[:3]}...")
            new_draft = {n: np.asarray(draft_states[n])
                         for n in self._draft.state_names}
        with self._lock:
            if self._stop:
                raise RuntimeError("GenerationServer is closed")
            if self._pending_states is not None:
                raise RuntimeError("hot swap already in progress")
            self._swap_done.clear()
            self._pending_states = (
                {n: np.asarray(states[n])
                 for n in self._decoder.state_names}, new_draft)
            self._lock.notify_all()
        if wait:
            return self._swap_done.wait(timeout)
        return True

    # -- graceful drain (scale-in / SIGTERM) --------------------------------
    @property
    def draining(self) -> bool:
        with self._lock:
            return self._draining

    def drain(self, wait: bool = True,
              timeout: Optional[float] = None) -> bool:
        """Stop ADMITTING new requests and (with `wait`) block until
        everything already accepted — active slots AND the queue — has
        run to completion.  This is the graceful-scale-in half of the
        PR 8 hot-swap machinery: a drained replica has delivered every
        stream it ever accepted, so retiring it afterwards fails
        nothing.  New submits raise RuntimeError (mapped to a
        RETRYABLE wire error by serving/replica.py, so a router
        resubmits on a survivor).  Returns True when fully drained
        within `timeout`; `resume()` re-opens admission for an aborted
        scale-in."""
        with self._lock:
            if self._stop:
                raise RuntimeError("GenerationServer is closed")
            self._draining = True
            self._lock.notify_all()
        if not wait:
            return True
        deadline = (None if timeout is None
                    else time.monotonic() + timeout)
        with self._lock:
            while not self._stop and (
                    self._queue
                    or any(s is not None for s in self._active)):
                left = (None if deadline is None
                        else deadline - time.monotonic())
                if left is not None and left <= 0:
                    return False
                # the scheduler notifies on evictions; the short cap
                # also covers the error-eviction path, which doesn't
                self._lock.wait(timeout=min(0.05, left)
                                if left is not None else 0.05)
            return not self._stop

    def resume(self) -> None:
        """Re-open admission after drain() (an aborted scale-in: the
        at-least-one-replica invariant found no survivor to retire
        onto)."""
        with self._lock:
            self._draining = False
            self._lock.notify_all()

    def stats(self) -> Dict[str, float]:
        """Serving telemetry view (docs/serving.md): request/token/tick
        counters, shed accounting, live occupancy, KV-pool state,
        prefix-cache hit accounting and speculative accept rates."""
        from ..core.executor import xla_compile_counts

        with self._lock:
            active = sum(1 for s in self._active if s is not None)
            qdepth = len(self._queue)
            draining = self._draining
        # process-wide compile counter diffed against this server's
        # post-warmup base: 0 == no XLA compile has happened since
        # warmup (the serving-side analogue of Executor.cache_stats()'s
        # recompiles_after_warmup; in a one-server process — a `cli
        # serve` replica — any nonzero value is a compile paid inside
        # request latency)
        recompiles = int(xla_compile_counts()["compiles"]
                         - self._compiles_after_warmup_base)
        out = {"requests": int(self._m_requests.value),
               "generated_tokens": int(self._m_tokens.value),
               "ticks": int(self._m_ticks.value),
               "shed": int(self._m_shed.value),
               "deadline_expired": int(self._m_deadline.value),
               "hot_swaps": int(self._m_swaps.value),
               "active_sequences": active,
               "queue_depth": qdepth,
               "kv_blocks_free": self._cache.free_blocks,
               "kv_blocks_total": self._cache.num_blocks,
               "kv_pool_utilization": self._cache.utilization(),
               "kv_dtype": getattr(self._decoder, "kv_dtype", "fp32"),
               "decode_kernel": getattr(self._decoder, "kernels", {})
               .get("paged_attention_decode", "xla"),
               "kv_bytes_resident": (self._cache.used_blocks
                                     * self._cache.bytes_per_block),
               "draft_proposed": int(self._m_proposed.value),
               "draft_accepted": int(self._m_accepted.value),
               "spec_k": self._spec_k if self._draft is not None else 0,
               "draining": draining,
               "recompiles_after_warmup": recompiles,
               "warm_start": bool(self.warm_start_dir)}
        out.update(self.warmup_stats)
        out.update(self._cache.prefix_stats())
        return out

    def outstanding_tokens(self) -> int:
        """Token budget not yet delivered (active + queued) — the load
        signal the replica router places on (least outstanding)."""
        with self._lock:
            out = sum(s.max_new - s.emitted
                      for s in self._active if s is not None)
            out += sum(s.max_new for s in self._queue)
        return out

    def close(self):
        with self._lock:
            self._stop = True
            self._lock.notify_all()
        self._worker.join(timeout=10)
        err = RuntimeError("GenerationServer closed")
        with self._lock:
            leftovers = ([s for s in self._active if s is not None]
                         + list(self._queue))
            self._active = [None] * self._slots
            self._queue.clear()
        for seq in leftovers:
            self._cache.release(seq)
            seq.stream._fail(err)
        self._cache.close()
        for fam in (_M_REQUESTS, _M_TOKENS, _M_TICKS, _M_SWAPS,
                    _M_LATENCY, _M_TTFT, _M_ACTIVE, _M_QDEPTH,
                    _M_DRAFT_PROPOSED, _M_DRAFT_ACCEPTED):
            fam.remove(server=self._sid)
        for reason in ("saturated", "deadline"):
            _M_SHED.remove(server=self._sid, reason=reason)
        # serving-kernel fallback series counted by this server's
        # decoders (kernels/registry.py Selection contract)
        for dec in (self._decoder, self._draft):
            sel = getattr(dec, "kernel_selection", None)
            if sel is not None:
                sel.close()

    # -- scheduler ----------------------------------------------------------
    def _shed_expired_locked(self, now: float) -> List[_Seq]:
        shed = []
        kept: deque = deque()
        for seq in self._queue:
            if seq.expires is not None and now >= seq.expires:
                shed.append(seq)
            else:
                kept.append(seq)
        self._queue = kept
        return shed

    def _admit_locked(self) -> List[_Seq]:
        """Move queued requests into free slots, FIFO, while KV blocks
        and slots last.  Head-of-line order is deliberate: skipping a
        big request to admit later small ones would starve it."""
        admitted = []
        n_active = sum(1 for s in self._active if s is not None)
        if self._static and n_active:
            return admitted   # drain-then-refill baseline
        if self._pending_states is not None:
            return admitted   # draining for a hot swap
        while self._queue:
            slot = next((i for i, s in enumerate(self._active)
                         if s is None), -1)
            if slot < 0:
                break
            seq = self._queue[0]
            if not self._cache.can_admit(seq.positions_needed,
                                         prompt_keys=seq.prompt_keys):
                break
            self._queue.popleft()
            try:
                with obs_attr.phase("generation", "kv_alloc"):
                    table, cached = self._cache.allocate_prefix(
                        seq, seq.positions_needed,
                        prompt_keys=seq.prompt_keys)
            except KVPoolExhausted:
                # can_admit/allocate_prefix disagreeing is a bug, but
                # an unserved admission must back off (head of queue,
                # retried next tick) — never kill the scheduler thread
                self._queue.appendleft(seq)
                break
            # prefix hit: the first `cached` positions already hold
            # this prompt's K/V — start the cursor there and skip
            # their prefill ticks.  A block-ALIGNED full-prompt hit
            # still re-runs the last prompt position (the step must
            # produce the first new token); that write lands in a
            # shared block with byte-identical values — the zero-copy
            # degenerate of copy-on-write.
            seq.cur = min(cached, seq.prompt_len - 1)
            seq.draft_next = seq.cur
            seq.slot = slot
            self._active[slot] = seq
            self._tables[slot] = table
            admitted.append(seq)
        return admitted

    def _evict_locked(self, seq: _Seq):
        self._active[seq.slot] = None
        self._tables[seq.slot] = 0
        seq.slot = -1
        with obs_attr.phase("generation", "kv_release"):
            self._cache.release(seq)

    def _loop(self):
        dec = self._decoder
        while True:
            with obs_attr.phase("generation", "admit"), self._lock:
                if self._stop:
                    return
                shed = self._shed_expired_locked(time.monotonic())
                admitted = self._admit_locked()
                seqs = [s for s in self._active if s is not None]
                swap = (self._pending_states
                        if self._pending_states is not None
                        and not seqs else None)
                qdepth = len(self._queue)
            metrics_on = obs_metrics.enabled()
            for seq in shed:
                self._m_deadline.inc()
                seq.stream._fail(RequestDeadlineExceeded(
                    "request deadline expired while queued for "
                    "admission"))
            if admitted:
                self._m_requests.inc(len(admitted))
            if metrics_on:
                self._m_qdepth.set(qdepth)
                self._m_active.set(len(seqs))
            if swap is not None:
                self._install_states(swap)
                continue
            if not seqs:
                with self._lock:
                    if (not self._queue and not self._stop
                            and self._pending_states is None):
                        self._lock.wait(timeout=self._idle_poll_s)
                continue
            try:
                # chaos hook fires inside _tick/_tick_spec, within the
                # attributed phase block: an error rule fails this
                # tick's sequences (they are evicted, their streams get
                # the error) but must never kill the scheduler thread
                if self._draft is None:
                    nxt = self._tick(seqs)
                else:
                    plans, preds = self._tick_spec(seqs)
            except Exception as e:
                with self._lock:
                    for seq in seqs:
                        self._evict_locked(seq)
                for seq in seqs:
                    seq.stream._fail(e)
                continue
            if self._draft is None:
                self._deliver(seqs, nxt, metrics_on)
            else:
                self._deliver_spec(plans, preds, metrics_on)
            # freshly-filled full prompt blocks become shareable the
            # moment the cursor passes their end (no-op once a
            # sequence has nothing pending or was evicted)
            for seq in seqs:
                self._cache.commit_prefix(seq, seq.cur)

    def _tick(self, seqs: List[_Seq]) -> np.ndarray:
        tokens = np.zeros(self._slots, np.int32)
        positions = np.zeros(self._slots, np.int32)
        temps = np.zeros(self._slots, np.float32)
        seeds = np.zeros(self._slots, np.uint32)
        active = np.zeros(self._slots, bool)
        for seq in seqs:
            tokens[seq.slot] = seq.tokens[seq.cur]
            positions[seq.slot] = seq.cur
            temps[seq.slot] = seq.temperature
            seeds[seq.slot] = seq.seed
            active[seq.slot] = True
        # attribution: dispatch is "prefill" while EVERY ticking
        # sequence is still teacher-forcing its prompt, else "decode"
        # (mixed ticks are decode work for at least one stream); the
        # host-side sync that materializes the sampled tokens is
        # "sample" — on an async backend that is where the device time
        # surfaces
        phase_name = ("prefill" if all(s.cur < s.prompt_len - 1
                                       for s in seqs) else "decode")
        with obs_tracing.span("serving.decode_tick", active=len(seqs)):
            with obs_attr.phase("generation", phase_name):
                fault_injector().fire("serving.decode")
                nxt, self._pool_k, self._pool_v = self._decoder.step(
                    self._states, self._pool_k, self._pool_v,
                    self._tables, positions, tokens, seeds, temps,
                    active)
            with obs_attr.phase("generation", "sample"):
                out = np.asarray(nxt)
        self._m_ticks.inc()
        return out

    def _deliver(self, seqs: List[_Seq], nxt: np.ndarray,
                 metrics_on: bool):
        now = time.perf_counter()
        delivered = 0
        finished = []
        with obs_attr.phase("generation", "deliver"):
            for seq in seqs:
                tok = int(nxt[seq.slot])
                seq.cur += 1
                if seq.cur < seq.prompt_len:
                    continue      # still prefilling: teacher-forced
                seq.tokens.append(tok)
                seq.emitted += 1
                delivered += 1
                if metrics_on and seq.emitted == 1:
                    with obs_tracing.activate(seq.trace_ctx):
                        self._m_ttft.observe(now - seq.t_submit)
                seq.stream._put(tok)
                if (seq.emitted >= seq.max_new
                        or (seq.eos_id is not None
                            and tok == seq.eos_id)):
                    finished.append(seq)
        if delivered:
            self._m_tokens.inc(delivered)
        if finished:
            with self._lock:
                for seq in finished:
                    self._evict_locked(seq)
                self._lock.notify_all()
            for seq in finished:
                self._finish_seq(seq, now, metrics_on)

    def _finish_seq(self, seq: _Seq, now: float, metrics_on: bool):
        """Close out a finished sequence: record the end-to-end
        ``serving.request`` span (child of the submitter's context, so
        router/replica hops join into one trace) and observe latency
        with that trace active — the histogram exemplar then points at
        this request's trace."""
        dur = now - seq.t_submit
        ctx = obs_tracing.record_span(
            "serving.request", seq.t_submit_wall, dur,
            parent=seq.trace_ctx, server=self._sid,
            tokens=seq.emitted) or seq.trace_ctx
        if metrics_on:
            with obs_tracing.activate(ctx):
                self._m_latency.observe(dur)
        seq.stream._finish()

    # -- speculative path ---------------------------------------------------
    def _tick_spec(self, seqs: List[_Seq]):
        """One speculative tick: draft catch-up + k greedy proposals
        per eligible slot, then ONE target step_window verifying the
        whole window.  Returns (plans, preds) for _deliver_spec.

        A plan is (seq, c, m, teacher, n_prop, proposals): `c` the
        cursor at tick start, `m` how many committed tokens sit at the
        window's head (teacher-forced), `n_prop` how many draft tokens
        follow them.  Sampled requests and prefill interiors get
        n_prop=0 — pure (chunked) teacher forcing."""
        w = self._spec_k + 1
        plans = []
        for seq in seqs:
            c = seq.cur
            m = len(seq.tokens) - c
            n_max = min(w, seq.positions_needed - c)
            teacher = min(m, n_max)
            greedy = seq.temperature == 0.0
            n_prop = (n_max - teacher
                      if greedy and teacher == m else 0)
            plans.append((seq, c, m, teacher, n_prop))

        # draft catch-up: teacher-force the draft over the window's
        # committed head so its KV tracks the target's (positions a
        # proposal step will re-write are excluded).  Normally one
        # chunk; the loop guards the at-most-one-position lag a fully
        # accepted window leaves behind.  Sampled sequences never
        # propose but STILL keep the draft warm: the prompt blocks
        # they commit to the prefix cache must hold valid draft KV for
        # the greedy sequences that later share them.
        with obs_attr.phase("generation", "draft_verify"):
            while True:
                todo = []
                for seq, c, m, teacher, n_prop in plans:
                    end = c + teacher - (1 if n_prop else 0)
                    if seq.draft_next < end:
                        todo.append(
                            (seq, min(end - seq.draft_next, w)))
                if not todo:
                    break
                pos = np.zeros(self._slots, np.int32)
                toks = np.zeros((self._slots, w), np.int32)
                nv = np.zeros(self._slots, np.int32)
                for seq, n in todo:
                    pos[seq.slot] = seq.draft_next
                    toks[seq.slot, :n] = seq.tokens[
                        seq.draft_next:seq.draft_next + n]
                    nv[seq.slot] = n
                _, self._dpool_k, self._dpool_v = \
                    self._draft.step_window(
                        self._draft_states, self._dpool_k,
                        self._dpool_v, self._tables, pos, toks,
                        np.zeros(self._slots, np.uint32),
                        np.zeros(self._slots, np.float32), nv)
                for seq, n in todo:
                    seq.draft_next += n

            # proposal micro-steps: the draft extends each eligible
            # slot greedily, one position per call, batched across
            # slots; step i feeds the committed frontier token first,
            # then its own previous proposal
            max_prop = max((p[4] for p in plans), default=0)
            proposals: Dict[object, List[int]] = {
                p[0]: [] for p in plans}
            for i in range(max_prop):
                pos = np.zeros(self._slots, np.int32)
                toks = np.zeros(self._slots, np.int32)
                act = np.zeros(self._slots, bool)
                stepping = []
                for seq, c, m, teacher, n_prop in plans:
                    if i >= n_prop:
                        continue
                    base = c + teacher - 1
                    pos[seq.slot] = base + i
                    toks[seq.slot] = (seq.tokens[base] if i == 0
                                      else proposals[seq][-1])
                    act[seq.slot] = True
                    stepping.append(seq)
                nxt, self._dpool_k, self._dpool_v = self._draft.step(
                    self._draft_states, self._dpool_k, self._dpool_v,
                    self._tables, pos, toks,
                    np.zeros(self._slots, np.uint32),
                    np.zeros(self._slots, np.float32), act)
                out = np.asarray(nxt)
                for seq in stepping:
                    proposals[seq].append(int(out[seq.slot]))
                    seq.draft_next = pos[seq.slot] + 1

        # ONE target dispatch verifies/extends every slot's window
        pos = np.zeros(self._slots, np.int32)
        toks = np.zeros((self._slots, w), np.int32)
        nv = np.zeros(self._slots, np.int32)
        temps = np.zeros(self._slots, np.float32)
        seeds = np.zeros(self._slots, np.uint32)
        for seq, c, m, teacher, n_prop in plans:
            window = seq.tokens[c:c + teacher] + proposals[seq]
            pos[seq.slot] = c
            toks[seq.slot, :len(window)] = window
            nv[seq.slot] = teacher + n_prop
            temps[seq.slot] = seq.temperature
            seeds[seq.slot] = seq.seed
        with obs_tracing.span("serving.decode_tick", active=len(seqs),
                              speculative=True):
            with obs_attr.phase("generation", "draft_verify"):
                fault_injector().fire("serving.decode")
                nxt, self._pool_k, self._pool_v = \
                    self._decoder.step_window(
                        self._states, self._pool_k, self._pool_v,
                        self._tables, pos, toks, seeds, temps, nv)
            with obs_attr.phase("generation", "sample"):
                preds = np.asarray(nxt)
        self._m_ticks.inc()
        full_plans = [(seq, c, m, teacher, n_prop, proposals[seq])
                      for seq, c, m, teacher, n_prop in plans]
        return full_plans, preds

    def _deliver_spec(self, plans, preds: np.ndarray, metrics_on: bool):
        """Greedy accept rule over each slot's verified window: keep
        emitting the target's prediction chain while it agrees with
        the next window token (committed tokens agree by construction;
        draft proposals are ACCEPTED on match), stop at the first
        disagreement with the target's own token as the bonus — the
        emitted stream is exactly the target's one-token-at-a-time
        greedy output."""
        now = time.perf_counter()
        delivered = 0
        proposed = accepted = 0
        finished = []
        with obs_attr.phase("generation", "deliver"):
            for seq, c, m, teacher, n_prop, props in plans:
                n_valid = teacher + n_prop
                window = seq.tokens[c:c + teacher] + props
                emitted: List[int] = []
                j_stop = n_valid - 1   # pure-teacher: no emission
                j = m - 1
                if j < n_valid:
                    while True:
                        tok = int(preds[seq.slot, j])
                        emitted.append(tok)
                        if (seq.emitted + len(emitted) >= seq.max_new
                                or (seq.eos_id is not None
                                    and tok == seq.eos_id)):
                            j_stop = j
                            break
                        if j + 1 < n_valid and tok == window[j + 1]:
                            j += 1   # proposal verified: keep going
                            continue
                        j_stop = j
                        break
                seq.cur = c + j_stop + 1
                proposed += n_prop
                if n_prop:
                    accepted += min(max(len(emitted) - 1, 0), n_prop)
                # the draft's KV is valid only where it processed
                # tokens that ended up committed — never past the
                # bonus token
                seq.draft_next = min(seq.draft_next, seq.cur)
                if emitted:
                    if metrics_on and seq.emitted == 0:
                        with obs_tracing.activate(seq.trace_ctx):
                            self._m_ttft.observe(now - seq.t_submit)
                    seq.tokens.extend(emitted)
                    seq.emitted += len(emitted)
                    delivered += len(emitted)
                    for tok in emitted:
                        seq.stream._put(tok)
                    if (seq.emitted >= seq.max_new
                            or (seq.eos_id is not None
                                and emitted[-1] == seq.eos_id)):
                        finished.append(seq)
        if delivered:
            self._m_tokens.inc(delivered)
        if proposed:
            self._m_proposed.inc(proposed)
        if accepted:
            self._m_accepted.inc(accepted)
        if finished:
            with self._lock:
                for seq in finished:
                    self._evict_locked(seq)
                self._lock.notify_all()
            for seq in finished:
                self._finish_seq(seq, now, metrics_on)

    def _install_states(self, pending):
        import jax

        states, draft_states = pending
        new = {n: jax.device_put(v, self._device)
               for n, v in states.items()}
        new_draft = ({n: jax.device_put(v, self._device)
                      for n, v in draft_states.items()}
                     if draft_states is not None else None)
        # cached prefix K/V is keyed by token content alone and is
        # valid for exactly ONE parameter version: flush it, or
        # post-swap requests would skip prefill into the OLD
        # checkpoint's K/V and silently emit wrong tokens
        self._cache.flush_prefix()
        with self._lock:
            self._states = new
            if new_draft is not None:
                self._draft_states = new_draft
            self._pending_states = None
            self._lock.notify_all()
        self._m_swaps.inc()
        self._swap_done.set()


# -- model dir format --------------------------------------------------------

def save_generation_model(dirname: str, states: Dict[str, np.ndarray],
                          spec: Dict,
                          draft_states: Optional[Dict] = None,
                          warm_start: bool = False,
                          place=None) -> str:
    """Persist a generation model: `generation.json` (architecture
    spec: vocab_size/d_model/n_heads/n_layers/d_inner, plus optional
    serving defaults block_size/max_blocks_per_seq/slots/kv_blocks/
    kv_dtype/spec_k and an optional `draft` sub-spec) and one npz of
    parameters.  With `draft_states`, the speculative-decoding draft
    model's parameters land in a second npz and spec["draft"] must
    name its architecture ({d_model, n_heads, n_layers[, d_inner]};
    vocab and block geometry are shared with the target).  The
    directory is what `cli serve` and the replica hot-swap verb
    consume.

    `warm_start=True` additionally ships the cold-start artifact: the
    serving executables are compiled once, at save time, into a
    persistent XLA compilation cache at ``<dirname>/xla_cache``
    (build_warm_start_artifact).  A replica later started from the dir
    deserializes them — its time-to-first-token is bounded by model
    load, not XLA compile."""
    os.makedirs(dirname, exist_ok=True)
    for key in ("vocab_size", "d_model", "n_heads", "n_layers"):
        if key not in spec:
            raise ValueError(f"spec missing {key!r}")
    if draft_states is not None:
        draft = spec.get("draft")
        if not isinstance(draft, dict):
            raise ValueError(
                "draft_states given but spec['draft'] (the draft "
                "architecture dict) is missing")
        for key in ("d_model", "n_heads", "n_layers"):
            if key not in draft:
                raise ValueError(f"spec['draft'] missing {key!r}")
        np.savez(os.path.join(dirname, MODEL_DRAFT_PARAMS_FILENAME),
                 **{n: np.asarray(v) for n, v in draft_states.items()})
    with open(os.path.join(dirname, MODEL_SPEC_FILENAME), "w") as f:
        json.dump(spec, f, indent=1, sort_keys=True)
    np.savez(os.path.join(dirname, MODEL_PARAMS_FILENAME),
             **{n: np.asarray(v) for n, v in states.items()})
    if warm_start:
        # the ROADMAP-4 cold-start enabler: compile the serving
        # executables ONCE at save time into <dirname>/xla_cache so
        # every scale-out replica deserializes instead of compiling
        build_warm_start_artifact(dirname, place=place)
    return dirname


def load_generation_model(dirname: str, with_draft: bool = False):
    """-> (states, spec) saved by save_generation_model; with
    `with_draft=True`, -> (states, spec, draft_states_or_None)."""
    with open(os.path.join(dirname, MODEL_SPEC_FILENAME)) as f:
        spec = json.load(f)
    with np.load(os.path.join(dirname, MODEL_PARAMS_FILENAME)) as z:
        states = {n: z[n] for n in z.files}
    if not with_draft:
        return states, spec
    draft_states = None
    dpath = os.path.join(dirname, MODEL_DRAFT_PARAMS_FILENAME)
    if os.path.exists(dpath):
        with np.load(dpath) as z:
            draft_states = {n: z[n] for n in z.files}
    return states, spec, draft_states


def build_warm_start_artifact(dirname: str, place=None) -> str:
    """Grow a saved generation model dir's warm-start artifact: build
    its serving decoder(s) and run the server warmup with the
    persistent XLA compilation cache pointed at
    ``<dirname>/xla_cache``, so the compiled executables serialize
    next to the parameters they serve.  The executables are keyed by
    shape, so the artifact covers the SPEC's serving geometry
    (slots/kv_blocks/block_size/...); a replica started with overrides
    compiles those shapes fresh.  Returns the artifact path."""
    cache = os.path.join(dirname, WARM_START_DIRNAME)
    srv = server_from_model_dir(dirname, place=place,
                                warm_cache_dir=cache)
    srv.close()
    return cache


def server_from_model_dir(dirname: str, *, block_size: Optional[int] = None,
                          max_blocks_per_seq: Optional[int] = None,
                          slots: Optional[int] = None,
                          kv_blocks: Optional[int] = None,
                          kv_dtype: Optional[str] = None,
                          spec_k: Optional[int] = None,
                          use_draft: bool = True,
                          warm_start: bool = True,
                          warm_cache_dir: Optional[str] = None,
                          **kw) -> GenerationServer:
    """Build a GenerationServer from a saved model dir.

    Resets the framework unique-name counters to rebuild the decoder
    under the names the parameters were saved with — intended for
    fresh serving processes (cli serve, replicas), not mid-session.
    `kv_dtype` overrides the spec's pool precision; a model dir with
    draft params arms speculative decoding unless `use_draft=False`.

    When the dir ships a warm-start artifact (``xla_cache/``, written
    by ``save_generation_model(warm_start=True)``) and no persistent
    compilation cache is already configured, the build+warmup runs
    with PADDLE_TPU_COMPILATION_CACHE_DIR pointed at the artifact and
    the executables DESERIALIZE instead of compiling
    (``warmup_stats['cache_misses'] == 0``); the prior flag value is
    restored afterwards.  ``warm_start=False`` opts out;
    ``warm_cache_dir`` forces a cache dir (creating it — how
    build_warm_start_artifact writes the artifact in the first
    place)."""
    from ..core import flags as core_flags
    from ..core import framework as fw
    from ..models.transformer import build_lm_paged_decoder

    cache = warm_cache_dir or ""
    if not cache and warm_start:
        shipped = os.path.join(dirname, WARM_START_DIRNAME)
        if os.path.isdir(shipped):
            cache = shipped
    prev = core_flags.get_flag("compilation_cache_dir")
    # an EXPLICIT warm_cache_dir always arms (build_warm_start_artifact
    # must write the artifact even when the operator runs with a global
    # cache configured); the shipped-artifact auto-arm never stomps a
    # configured cache
    armed = bool(cache) and (warm_cache_dir is not None or not prev)
    states, spec, draft_states = load_generation_model(
        dirname, with_draft=True)
    bs = int(block_size or spec.get("block_size", 16))
    nb = int(max_blocks_per_seq
             or spec.get("max_blocks_per_seq",
                         -(-int(spec.get("max_len", 256)) // bs)))
    kvd = kv_dtype or spec.get("kv_dtype")
    try:
        if armed:
            core_flags.set_flags({"compilation_cache_dir": cache})
        fw.reset_unique_names()
        _, decoder = build_lm_paged_decoder(
            spec["vocab_size"], bs, nb, d_model=spec["d_model"],
            n_heads=spec["n_heads"], n_layers=spec["n_layers"],
            d_inner=spec.get("d_inner"), kv_dtype=kvd)
        draft_decoder = None
        if draft_states is not None and use_draft:
            dspec = spec["draft"]
            fw.reset_unique_names()
            _, draft_decoder = build_lm_paged_decoder(
                spec["vocab_size"], bs, nb, d_model=dspec["d_model"],
                n_heads=dspec["n_heads"], n_layers=dspec["n_layers"],
                d_inner=dspec.get("d_inner"), kv_dtype=kvd)
        else:
            draft_states = None
        server = GenerationServer(
            decoder, states,
            slots=int(slots or spec.get("slots", 8)),
            kv_blocks=int(kv_blocks or spec.get("kv_blocks", 64)),
            draft_decoder=draft_decoder, draft_states=draft_states,
            spec_k=(spec_k if spec_k is not None
                    else spec.get("spec_k")), **kw)
    finally:
        if armed:
            # the executables are loaded; later in-process compiles
            # must follow the caller's own cache configuration
            core_flags.set_flags({"compilation_cache_dir": prev})
    if armed:
        server.warm_start_dir = cache
    _publish_static_decode_floor(spec, server)
    return server


def _publish_static_decode_floor(spec: dict, server: GenerationServer):
    """Publish the static roofline floor for the decode phase so the
    collector's calibration detector can band measured-vs-static
    (docs/observability.md "Time attribution").  Best-effort: the cost
    model not covering a spec must never block serving."""
    try:
        from ..analysis.cost_model import (analyze_generation_spec,
                                           roofline_seconds,
                                           serving_kernel_cost)
        rows = analyze_generation_spec(
            spec, slots=server._slots)["kernels"]
        step = rows[0]
        # band against the backend the DECODER actually selected (the
        # registry's spec-level resolution can disagree with a build
        # that fell back on shape) — the calibration ratio must compare
        # measured time to the floor of what runs, not of the oracle
        backend = ("pallas" if getattr(server._decoder, "kernels", {})
                   .get("paged_attention_decode") == "pallas"
                   else "xla")
        if step.get("backend") != backend:
            step = serving_kernel_cost(
                "paged_decode_step", spec, slots=server._slots,
                context=(int(spec.get("block_size", 16))
                         * int(spec.get("max_blocks_per_seq", 64)))
                // 2,
                kv_dtype=str(spec.get("kv_dtype") or "fp32"),
                backend=backend)
        obs_attr.publish_static_floor("generation", {
            "decode": roofline_seconds(step["flops"], step["bytes"]),
        })
    except Exception as e:
        warnings.warn(f"static decode floor unavailable: {e!r}")
