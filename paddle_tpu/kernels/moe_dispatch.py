"""Fused MoE gate + capacity dispatch as one Pallas kernel.

The oracle (parallel/moe.moe_gate + the dispatch einsum in moe_dense)
lowers to ~15 XLA ops that materialize the [T, E] routing tensors and
the [T, E, C] one-hot dispatch tensor in HBM before the dispatch
einsum reads them back — at serving batch sizes the routing tensors
cost more HBM round-trips than the math is worth (the static analyzer
flags moe_ffn memory-bound).  This kernel runs the WHOLE pass — gate
logits, softmax, top-k argmax, capacity-position cumsum, dispatch
one-hots, the dispatch contraction and the aux loss — in one
pallas_call with every intermediate resident in VMEM, emitting only
what the expert matmuls and the combine step actually consume:
`expert_in` [E, C, D], `combine` [T, E, C] and the aux-loss scalar.

The math is LINE-FOR-LINE parallel/moe.moe_gate (top-1 Switch or
top-2 GShard with the after-all-first-choices position rule) plus
moe_dense's `einsum("td,tec->ecd")` dispatch, which keeps the fused
path bit-identical to the oracle composition
(tests/test_serving_kernels.py pins it under interpret mode).

Selection and fallback accounting: kernels/registry.py
("moe_gate_dispatch"); oversized routing tensors or non-f32 operands
fall back to the oracle, counted.
"""
from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

from .registry import register_kernel

__all__ = ["moe_dispatch_supports", "build_moe_gate_dispatch"]

# everything lives in VMEM at once (that is the point); past this the
# routing tensors need tiling and the capacity cumsum stops being one
# in-register scan — fall back to the oracle instead
_VMEM_BUDGET_BYTES = 10 * 1024 * 1024


def _vmem_bytes(T: int, D: int, E: int, C: int) -> int:
    # x, gate_w, expert_in, combine + the [T, E] routing intermediates
    return 4 * (T * D + D * E + E * C * D + 2 * T * E * C + 6 * T * E)


def moe_dispatch_supports(*, tokens: int, d_model: int,
                          num_experts: int, capacity: int,
                          top_k: int = 1, dtype: str = "float32",
                          platform: str = "cpu", **_) -> Optional[str]:
    if top_k not in (1, 2):
        return "top_k"
    if dtype != "float32":
        return "dtype"
    if _vmem_bytes(tokens, d_model, num_experts, capacity) \
            > _VMEM_BUDGET_BYTES:
        return "vmem_routing"
    if platform == "tpu":
        if d_model % 128:
            return "lane_misaligned"
        if tokens % 8:
            return "sublane_misaligned"
    return None


def _gate_dispatch_kernel(x_ref, gw_ref, ei_ref, cb_ref, aux_ref, *,
                          num_experts, capacity, top_k):
    x = x_ref[...]
    logits = jnp.dot(x, gw_ref[...])                     # [T, E]
    probs = jax.nn.softmax(logits.astype(jnp.float32), axis=-1)
    idx1 = jnp.argmax(probs, axis=-1)
    mask1 = jax.nn.one_hot(idx1, num_experts, dtype=jnp.float32)
    g1 = jnp.sum(probs * mask1, axis=-1)

    pos1 = jnp.sum((jnp.cumsum(mask1, axis=0) - 1.0) * mask1, axis=-1)
    keep1 = (pos1 < capacity).astype(jnp.float32)
    pos1_1h = jax.nn.one_hot(pos1.astype(jnp.int32), capacity,
                             dtype=jnp.float32)
    d1 = mask1[:, :, None] * pos1_1h[:, None, :] * keep1[:, None, None]

    frac_tokens = jnp.mean(mask1, axis=0)
    frac_probs = jnp.mean(probs, axis=0)
    aux_ref[0, 0] = num_experts * jnp.sum(frac_tokens * frac_probs)

    if top_k == 1:
        dispatch = d1
        combine = d1 * g1[:, None, None]
    else:
        probs2 = probs * (1.0 - mask1)
        idx2 = jnp.argmax(probs2, axis=-1)
        mask2 = jax.nn.one_hot(idx2, num_experts, dtype=jnp.float32)
        g2 = jnp.sum(probs * mask2, axis=-1)
        first_count = jnp.sum(mask1, axis=0)
        pos2 = jnp.sum(((jnp.cumsum(mask2, axis=0) - 1.0)
                        + first_count[None, :]) * mask2, axis=-1)
        keep2 = (pos2 < capacity).astype(jnp.float32)
        pos2_1h = jax.nn.one_hot(pos2.astype(jnp.int32), capacity,
                                 dtype=jnp.float32)
        d2 = (mask2[:, :, None] * pos2_1h[:, None, :]
              * keep2[:, None, None])
        denom = jnp.maximum(g1 + g2, 1e-9)
        dispatch = d1 + d2
        combine = (d1 * (g1 / denom)[:, None, None]
                   + d2 * (g2 / denom)[:, None, None])

    ei_ref[...] = jnp.einsum("td,tec->ecd", x.astype(jnp.float32),
                             dispatch)
    cb_ref[...] = combine


@register_kernel("moe_gate_dispatch", moe_dispatch_supports)
def build_moe_gate_dispatch(*, tokens: int, d_model: int,
                            num_experts: int, capacity: int,
                            top_k: int = 1, interpret: bool = False,
                            platform: str = "cpu", **_):
    """-> fused(x [T, D] f32, gate_w [D, E] f32) ->
    (expert_in [E, C, D] f32, combine [T, E, C] f32, aux [1, 1] f32)."""
    T, D, E, C = int(tokens), int(d_model), int(num_experts), \
        int(capacity)
    kern = functools.partial(_gate_dispatch_kernel, num_experts=E,
                             capacity=C, top_k=int(top_k))

    def fused(x, gate_w):
        return pl.pallas_call(
            kern,
            out_shape=[
                jax.ShapeDtypeStruct((E, C, D), jnp.float32),
                jax.ShapeDtypeStruct((T, E, C), jnp.float32),
                jax.ShapeDtypeStruct((1, 1), jnp.float32),
            ],
            interpret=interpret,
        )(x, gate_w)

    return fused
