"""Flash attention as a Pallas TPU kernel (fwd + bwd, custom_vjp).

The reference composes attention from primitive ops
(/root/reference/python/paddle/v2/fluid/nets.py:162-219
scaled_dot_product_attention: matmul -> softmax -> matmul), which
materializes the [seq_q, seq_k] score matrix in main memory.  On TPU that
matrix is the HBM-bandwidth bottleneck; this kernel keeps score tiles in
VMEM and streams K/V blocks through the MXU with an online softmax, so HBM
traffic is O(seq·d) instead of O(seq²).

Layout: [batch, seq, heads, head_dim] (matches parallel/ring_attention.py).
Internally folded to [batch·heads, seq, head_dim]; grid = (bh, q_blocks,
k_blocks) with the k dimension innermost so the VMEM accumulator scratch
persists across K/V blocks of one query tile.

Backward is the standard flash recomputation: forward saves only the
per-row logsumexp; dq / dk / dv are three more streaming kernels.

Falls back to a plain XLA composition when shapes don't tile (seq not a
multiple of the block) or no TPU is present and interpret mode is off.
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

try:  # pallas TPU backend (absent on some CPU-only builds)
    from jax.experimental.pallas import tpu as pltpu
except ImportError:  # pragma: no cover
    pltpu = None

__all__ = ["flash_attention", "flash_attention_reference"]

NEG_INF = -1e30  # finite mask value: keeps exp()/max() NaN-free in-kernel
# measured on v5e at seq 4096, d 128, bf16 (async-chain, distinct inputs):
# 512x1024 blocks run 6.5 ms vs 21.8 ms at 128x128 and 15.1 ms for the XLA
# composition — big K blocks amortize the per-step acc rescale + m/l
# bookkeeping, big Q blocks amortize K/V streaming
DEFAULT_BLOCK_Q = 512
DEFAULT_BLOCK_K = 1024


def _select_blocks(sq: int, sk: int, d: int) -> tuple[int, int]:
    """(block_q, block_k) keyed on the attention shape — the r4 ridge
    work measured the 512x1024 defaults (tuned at seq 4096 / d 128)
    leaving throughput on the table at longer sequences: at seq 8192,
    d 128 the fwd+bwd layer step runs +8% at 1024x2048 (2048x2048 fails
    to compile: the f32 score tile alone is 16 MB of VMEM).  Larger K
    blocks amortize the per-step rescale bookkeeping, and the benefit
    grows with how many K blocks stream past a resident Q tile."""
    if sk >= 8192:
        return 1024, 2048
    return DEFAULT_BLOCK_Q, DEFAULT_BLOCK_K


# ---------------------------------------------------------------------------
# forward
# ---------------------------------------------------------------------------

def _fwd_kernel(q_ref, k_ref, v_ref, o_ref, lse_ref, acc_ref, m_ref, l_ref,
                *, scale, causal, block_q, block_k, nk, pack, d_head):
    """pack >= 2 folds `pack` heads side-by-side in the trailing dim
    (q/k/v tiles [block, pack*d_head]): loads/stores fill the 128-lane
    dim even at d_head 64, and the online softmax runs per packed head
    on its own [block_q, block_k] score tile (block-diagonal — heads
    never mix).  m/l scratch columns are banded per head."""
    i, j = pl.program_id(1), pl.program_id(2)
    cw = 128 // pack  # scratch column band per packed head

    @pl.when(j == 0)
    def _init():
        acc_ref[:] = jnp.zeros_like(acc_ref)
        m_ref[:] = jnp.full_like(m_ref, NEG_INF)
        l_ref[:] = jnp.zeros_like(l_ref)

    def _compute():
        # operands stay in their storage dtype: bf16 x bf16 -> f32 rides
        # the MXU's native path (an .astype(f32) here forces the ~8x
        # slower fp32 MXU passes — measured 0.54x vs XLA before, 1.8x+
        # after); accumulation is f32 via preferred_element_type
        q = q_ref[0]
        k = k_ref[0]
        v = v_ref[0]
        if causal:
            rows = i * block_q + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 0)
            cols = j * block_k + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 1)
            keep = rows >= cols
        for hs in range(pack):
            sl = slice(hs * d_head, (hs + 1) * d_head)
            s = jax.lax.dot_general(
                q[:, sl], k[:, sl], (((1,), (1,)), ((), ())),
                preferred_element_type=jnp.float32) * scale
            if causal:
                s = jnp.where(keep, s, NEG_INF)
            m_prev = m_ref[:, hs * cw:hs * cw + 1]
            l_prev = l_ref[:, hs * cw:hs * cw + 1]
            m_cur = jnp.max(s, axis=1, keepdims=True)
            m_new = jnp.maximum(m_prev, m_cur)
            alpha = jnp.exp(m_prev - m_new)
            p = jnp.exp(s - m_new)
            l_new = alpha * l_prev + jnp.sum(p, axis=1, keepdims=True)
            acc_ref[:, sl] = acc_ref[:, sl] * alpha + jax.lax.dot_general(
                p.astype(v.dtype), v[:, sl], (((1,), (0,)), ((), ())),
                preferred_element_type=jnp.float32)
            m_ref[:, hs * cw:(hs + 1) * cw] = jnp.broadcast_to(
                m_new, (block_q, cw))
            l_ref[:, hs * cw:(hs + 1) * cw] = jnp.broadcast_to(
                l_new, (block_q, cw))

    if causal:
        # skip K/V blocks strictly above the diagonal of this query tile
        @pl.when(j * block_k <= i * block_q + (block_q - 1))
        def _():
            _compute()
    else:
        _compute()

    @pl.when(j == nk - 1)
    def _finish():
        for hs in range(pack):
            sl = slice(hs * d_head, (hs + 1) * d_head)
            l = l_ref[:, hs * cw:hs * cw + 1]
            l_safe = jnp.where(l == 0.0, 1.0, l)
            o_ref[0, :, sl] = (acc_ref[:, sl] / l_safe).astype(o_ref.dtype)
            # (block_q, pack) tile: one lse column per packed head
            lse_ref[0, :, hs:hs + 1] = (m_ref[:, hs * cw:hs * cw + 1]
                                        + jnp.log(l_safe))


def _kv_index_map(causal, block_q, block_k):
    """K/V block index for grid step (b, i, j).

    Causal: clamp j to the diagonal block of query tile i.  Steps above the
    diagonal (compute skipped by pl.when) then repeat the previous block
    index, and the Pallas pipeline skips the HBM->VMEM copy for a repeated
    index — masked K/V tiles cost no bandwidth.
    """
    if not causal:
        return lambda b, i, j: (b, j, 0)
    return lambda b, i, j: (
        b, jnp.minimum(j, (i * block_q + (block_q - 1)) // block_k), 0)


def _fwd_pallas(q, k, v, scale, causal, block_q, block_k, interpret,
                pack=1):
    bh, sq, d = q.shape          # d = pack * d_head (packed layout)
    sk = k.shape[1]
    nq, nk = sq // block_q, sk // block_k
    kern = functools.partial(_fwd_kernel, scale=scale, causal=causal,
                             block_q=block_q, block_k=block_k, nk=nk,
                             pack=pack, d_head=d // pack)
    kv_map = _kv_index_map(causal, block_q, block_k)
    o, lse = pl.pallas_call(
        kern,
        grid=(bh, nq, nk),
        in_specs=[
            pl.BlockSpec((1, block_q, d), lambda b, i, j: (b, i, 0)),
            pl.BlockSpec((1, block_k, d), kv_map),
            pl.BlockSpec((1, block_k, d), kv_map),
        ],
        out_specs=[
            pl.BlockSpec((1, block_q, d), lambda b, i, j: (b, i, 0)),
            pl.BlockSpec((1, block_q, pack), lambda b, i, j: (b, i, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((bh, sq, d), q.dtype),
            jax.ShapeDtypeStruct((bh, sq, pack), jnp.float32),
        ],
        scratch_shapes=[
            pltpu.VMEM((block_q, d), jnp.float32),
            pltpu.VMEM((block_q, 128), jnp.float32),
            pltpu.VMEM((block_q, 128), jnp.float32),
        ] if pltpu is not None else [],
        interpret=interpret,
    )(q, k, v)
    return o, lse


# ---------------------------------------------------------------------------
# backward
# ---------------------------------------------------------------------------

def _dq_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref, dq_ref,
               dq_acc, *, scale, causal, block_q, block_k, nk, pack,
               d_head):
    i, j = pl.program_id(1), pl.program_id(2)

    @pl.when(j == 0)
    def _init():
        dq_acc[:] = jnp.zeros_like(dq_acc)

    def _compute():
        # native-dtype MXU operands, f32 accumulate (see _fwd_kernel)
        q = q_ref[0]
        k = k_ref[0]
        v = v_ref[0]
        do = do_ref[0]
        lse = lse_ref[0]        # (block_q, pack)
        delta = delta_ref[0]    # (block_q, pack)
        if causal:
            rows = i * block_q + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 0)
            cols = j * block_k + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 1)
            keep = rows >= cols
        for hs in range(pack):
            sl = slice(hs * d_head, (hs + 1) * d_head)
            s = jax.lax.dot_general(
                q[:, sl], k[:, sl], (((1,), (1,)), ((), ())),
                preferred_element_type=jnp.float32) * scale
            if causal:
                s = jnp.where(keep, s, NEG_INF)
            p = jnp.exp(s - lse[:, hs:hs + 1])
            dp = jax.lax.dot_general(
                do[:, sl], v[:, sl], (((1,), (1,)), ((), ())),
                preferred_element_type=jnp.float32)
            ds = p * (dp - delta[:, hs:hs + 1]) * scale
            dq_acc[:, sl] += jax.lax.dot_general(
                ds.astype(k.dtype), k[:, sl], (((1,), (0,)), ((), ())),
                preferred_element_type=jnp.float32)

    if causal:
        @pl.when(j * block_k <= i * block_q + (block_q - 1))
        def _():
            _compute()
    else:
        _compute()

    @pl.when(j == nk - 1)
    def _finish():
        dq_ref[0] = dq_acc[:].astype(dq_ref.dtype)


def _dkv_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
                dk_ref, dv_ref, dk_acc, dv_acc,
                *, scale, causal, block_q, block_k, nq, pack, d_head):
    # grid = (bh, k_blocks, q_blocks): q innermost so dk/dv scratch persists
    i, j = pl.program_id(1), pl.program_id(2)   # i: k block, j: q block

    @pl.when(j == 0)
    def _init():
        dk_acc[:] = jnp.zeros_like(dk_acc)
        dv_acc[:] = jnp.zeros_like(dv_acc)

    def _compute():
        # native-dtype MXU operands, f32 accumulate (see _fwd_kernel)
        q = q_ref[0]
        k = k_ref[0]
        v = v_ref[0]
        do = do_ref[0]
        lse = lse_ref[0]        # (pack, block_q) — transposed layout
        delta = delta_ref[0]    # (pack, block_q)
        if causal:
            krows = i * block_k + jax.lax.broadcasted_iota(
                jnp.int32, (block_k, block_q), 0)
            qcols = j * block_q + jax.lax.broadcasted_iota(
                jnp.int32, (block_k, block_q), 1)
            keep = qcols >= krows
        for hs in range(pack):
            sl = slice(hs * d_head, (hs + 1) * d_head)
            # transposed tile: rows = k positions, cols = q positions
            st = jax.lax.dot_general(
                k[:, sl], q[:, sl], (((1,), (1,)), ((), ())),
                preferred_element_type=jnp.float32) * scale
            if causal:
                st = jnp.where(keep, st, NEG_INF)
            pt = jnp.exp(st - lse[hs:hs + 1, :])
            dv_acc[:, sl] += jax.lax.dot_general(
                pt.astype(do.dtype), do[:, sl], (((1,), (0,)), ((), ())),
                preferred_element_type=jnp.float32)
            dpt = jax.lax.dot_general(
                v[:, sl], do[:, sl], (((1,), (1,)), ((), ())),
                preferred_element_type=jnp.float32)
            dst = pt * (dpt - delta[hs:hs + 1, :]) * scale
            dk_acc[:, sl] += jax.lax.dot_general(
                dst.astype(q.dtype), q[:, sl], (((1,), (0,)), ((), ())),
                preferred_element_type=jnp.float32)

    if causal:
        # a k block gets gradient only from q blocks at/below its diagonal
        @pl.when(j * block_q + (block_q - 1) >= i * block_k)
        def _():
            _compute()
    else:
        _compute()

    @pl.when(j == nq - 1)
    def _finish():
        dk_ref[0] = dk_acc[:].astype(dk_ref.dtype)
        dv_ref[0] = dv_acc[:].astype(dv_ref.dtype)


def _bwd_pallas(q, k, v, o, lse, do, scale, causal, block_q, block_k,
                interpret, pack=1):
    bh, sq, d = q.shape          # d = pack * d_head
    sk = k.shape[1]
    d_head = d // pack
    nq, nk = sq // block_q, sk // block_k
    # lse arrives as (bh, sq, pack); delta matches (per packed head),
    # plus (bh, pack, sq) transposed copies for the dkv kernel's k-major
    # tiles
    delta = jnp.sum(
        (do.astype(jnp.float32) * o.astype(jnp.float32)).reshape(
            bh, sq, pack, d_head),
        axis=-1)
    lse_t = jnp.transpose(lse, (0, 2, 1))
    delta_t = jnp.transpose(delta, (0, 2, 1))

    kv_map = _kv_index_map(causal, block_q, block_k)
    dq = pl.pallas_call(
        functools.partial(_dq_kernel, scale=scale, causal=causal,
                          block_q=block_q, block_k=block_k, nk=nk,
                          pack=pack, d_head=d_head),
        grid=(bh, nq, nk),
        in_specs=[
            pl.BlockSpec((1, block_q, d), lambda b, i, j: (b, i, 0)),
            pl.BlockSpec((1, block_k, d), kv_map),
            pl.BlockSpec((1, block_k, d), kv_map),
            pl.BlockSpec((1, block_q, d), lambda b, i, j: (b, i, 0)),
            pl.BlockSpec((1, block_q, pack), lambda b, i, j: (b, i, 0)),
            pl.BlockSpec((1, block_q, pack), lambda b, i, j: (b, i, 0)),
        ],
        out_specs=pl.BlockSpec((1, block_q, d), lambda b, i, j: (b, i, 0)),
        out_shape=jax.ShapeDtypeStruct((bh, sq, d), q.dtype),
        scratch_shapes=[pltpu.VMEM((block_q, d), jnp.float32)]
        if pltpu is not None else [],
        interpret=interpret,
    )(q, k, v, do, lse, delta)

    if causal:
        # q blocks strictly below a k block's diagonal are masked; clamping
        # their index repeats the previous block -> the pipeline skips the
        # copy (mirror of _kv_index_map for the transposed iteration)
        def _clamped(i, j):
            # min() keeps the index in range when sk > sq (the last k
            # blocks' diagonals lie past the final q block); out-of-range
            # block indices are undefined behavior on Mosaic even for
            # compute-masked steps
            return jnp.minimum(jnp.maximum(j, (i * block_k) // block_q),
                               nq - 1)

        def q_map(b, i, j):
            return (b, _clamped(i, j), 0)

        def q_vec_map(b, i, j):
            return (b, 0, _clamped(i, j))
    else:
        def q_map(b, i, j):
            return (b, j, 0)

        def q_vec_map(b, i, j):
            return (b, 0, j)

    dk, dv = pl.pallas_call(
        functools.partial(_dkv_kernel, scale=scale, causal=causal,
                          block_q=block_q, block_k=block_k, nq=nq,
                          pack=pack, d_head=d_head),
        grid=(bh, nk, nq),
        in_specs=[
            pl.BlockSpec((1, block_q, d), q_map),
            pl.BlockSpec((1, block_k, d), lambda b, i, j: (b, i, 0)),
            pl.BlockSpec((1, block_k, d), lambda b, i, j: (b, i, 0)),
            pl.BlockSpec((1, block_q, d), q_map),
            pl.BlockSpec((1, pack, block_q), q_vec_map),
            pl.BlockSpec((1, pack, block_q), q_vec_map),
        ],
        out_specs=[
            pl.BlockSpec((1, block_k, d), lambda b, i, j: (b, i, 0)),
            pl.BlockSpec((1, block_k, d), lambda b, i, j: (b, i, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((bh, sk, d), k.dtype),
            jax.ShapeDtypeStruct((bh, sk, d), v.dtype),
        ],
        scratch_shapes=[
            pltpu.VMEM((block_k, d), jnp.float32),
            pltpu.VMEM((block_k, d), jnp.float32),
        ] if pltpu is not None else [],
        interpret=interpret,
    )(q, k, v, do, lse_t, delta_t)
    return dq, dk, dv


# ---------------------------------------------------------------------------
# custom_vjp wrapper over [bh, seq, d]
# ---------------------------------------------------------------------------

@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6, 7, 8))
def _flash(q, k, v, scale, causal, block_q, block_k, interpret, pack):
    o, _ = _fwd_pallas(q, k, v, scale, causal, block_q, block_k,
                       interpret, pack)
    return o


def _flash_fwd(q, k, v, scale, causal, block_q, block_k, interpret, pack):
    o, lse = _fwd_pallas(q, k, v, scale, causal, block_q, block_k,
                         interpret, pack)
    return o, (q, k, v, o, lse)


def _flash_bwd(scale, causal, block_q, block_k, interpret, pack, res, do):
    q, k, v, o, lse = res
    return _bwd_pallas(q, k, v, o, lse, do, scale, causal,
                       block_q, block_k, interpret, pack)


_flash.defvjp(_flash_fwd, _flash_bwd)


# ---------------------------------------------------------------------------
# public API
# ---------------------------------------------------------------------------

def flash_attention_reference(q, k, v, causal=False, scale=None):
    """XLA-composed fallback / test oracle; layout [b, s, h, d]."""
    scale = q.shape[-1] ** -0.5 if scale is None else scale
    s = jnp.einsum("bqhd,bkhd->bhqk", q.astype(jnp.float32),
                   k.astype(jnp.float32)) * scale
    if causal:
        ql, kl = q.shape[1], k.shape[1]
        mask = jnp.arange(ql)[:, None] >= jnp.arange(kl)[None, :]
        s = jnp.where(mask, s, NEG_INF)
    p = jax.nn.softmax(s, axis=-1)
    return jnp.einsum("bhqk,bkhd->bqhd", p,
                      v.astype(jnp.float32)).astype(q.dtype)


# below this K/V length the materialized-scores XLA composition measured
# faster than the Pallas kernel on v5e (the S^2 matrix still fits cache-
# friendly tiles and XLA's single fusion beats the grid-loop overhead);
# above it the kernel wins and keeps winning as S^2 grows (1.5-2.3x at
# 4k-8k, and 32k+ only runs at all on the kernel) — run_attention.py
MIN_PALLAS_SEQ_K = 2048


def _largest_tile(seq, block, align=128):
    """Largest multiple of `align` that divides `seq`, capped at `block`;
    0 when none exists (seq not `align`-aligned)."""
    for m in range(min(block, seq) // align, 0, -1):
        if seq % (m * align) == 0:
            return m * align
    return 0


def flash_attention(q, k, v, causal=False, scale=None,
                    block_q=None, block_k=None,
                    interpret=None, min_seq_k=MIN_PALLAS_SEQ_K):
    """Flash attention over [batch, seq, heads, head_dim] tensors.

    Streams K/V through VMEM with online softmax (fwd) and recomputation
    (bwd).  Falls back to the XLA composition when not on a TPU backend
    (unless `interpret=True` asks for the pallas interpreter, e.g. tests),
    when the sequence doesn't tile onto MXU-aligned blocks, or when the
    K/V length is below `min_seq_k` (where the XLA composition measures
    faster; pass min_seq_k=0 to force the kernel).  Block sizes default
    to the shape-keyed measured table (`_select_blocks`); explicit
    block_q/block_k override it.
    """
    b, sq, h, d = q.shape
    sk = k.shape[1]
    from ..core.flags import get_flag
    sel_q, sel_k = _select_blocks(sq, sk, d)
    if int(get_flag("flash_block_q")) > 0:
        sel_q = int(get_flag("flash_block_q"))
    if int(get_flag("flash_block_k")) > 0:
        sel_k = int(get_flag("flash_block_k"))
    block_q = sel_q if block_q is None else block_q
    block_k = sel_k if block_k is None else block_k
    block_q = min(block_q, sq)
    block_k = min(block_k, sk)
    scale_v = float(d ** -0.5 if scale is None else scale)
    interp = bool(interpret)
    if not interp and jax.default_backend() != "tpu":
        # Mosaic only lowers on TPU, and emulating the grid loop on CPU/GPU
        # is far slower than one fused XLA attention — fall back unless the
        # caller opted into the pallas interpreter (interpret=True, tests)
        return flash_attention_reference(q, k, v, causal, scale_v)
    if not interp and sk < min_seq_k:
        return flash_attention_reference(q, k, v, causal, scale_v)
    if not interp and (sq % block_q or sk % block_k):
        # seqs that are MXU-aligned but not multiples of the large
        # default blocks (e.g. sk=2560 vs block_k=1024) must shrink to
        # the largest 128-multiple divisor, not fall back to the
        # score-materializing composition — above the crossover that
        # fallback is exactly what the kernel exists to avoid
        block_q = _largest_tile(sq, block_q) or block_q
        block_k = _largest_tile(sk, block_k) or block_k
    tiles_ok = sq % block_q == 0 and sk % block_k == 0
    if not interp:
        # Mosaic lowering wants MXU-aligned tiles; route small/ragged
        # shapes to the XLA composition instead of failing at jit time
        tiles_ok = (tiles_ok and block_q % 128 == 0 and block_k % 128 == 0
                    and d % 8 == 0)
    if (pltpu is None or not tiles_ok
            or k.shape != (b, sk, h, d) or v.shape != (b, sk, h, d)):
        return flash_attention_reference(q, k, v, causal, scale_v)
    # head-pair packing: at d_head 64 the [block, d] tiles fill half the
    # 128-lane dim; folding two heads side-by-side ([b*h/2, s, 128])
    # fills the lanes for every load/store while the per-head score
    # tiles stay block-diagonal inside the kernel (flash_pack_heads)
    pack = 2 if (d == 64 and h % 2 == 0
                 and bool(get_flag("flash_pack_heads"))) else 1

    def fold(x, s_len):
        # ADJACENT heads pair up by a pure reshape ((h, d) dims are
        # contiguous), so packing costs exactly the transposes the
        # unpacked path already pays — and the one real transpose now
        # moves a full-128-lane last dim instead of a half-filled one
        x = x.reshape(b, s_len, h // pack, pack * d)
        x = jnp.transpose(x, (0, 2, 1, 3))
        return x.reshape(b * h // pack, s_len, pack * d)

    o = _flash(fold(q, sq), fold(k, sk), fold(v, sk), scale_v,
               bool(causal), block_q, block_k, interp, pack)
    o = jnp.transpose(o.reshape(b, h // pack, sq, pack * d),
                      (0, 2, 1, 3))
    return o.reshape(b, sq, h, d)
