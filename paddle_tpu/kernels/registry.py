"""Serving-kernel registry: selection + per-op fallback accounting.

The flash-attention kernel picks Pallas-vs-XLA inside its own entry
point; the serving tier (paged-attention decode, fused MoE dispatch,
fused optimizer update) instead routes every selection through ONE
registry so the policy is uniform and observable:

  * each kernel registers a `supports(**ctx) -> None | reason` predicate
    over the shapes/dtypes it can run and a `build(**ctx)` factory;
  * `select(name, **ctx)` resolves the `serving_kernels` flag
    (PADDLE_TPU_SERVING_KERNELS: "auto" arms on TPU backends only, "on"
    arms everywhere — CPU runs the kernels under Pallas interpret mode,
    which is how tier-1 exercises them — "off" never arms);
  * an armed-but-unsupported combination falls back to the XLA oracle
    path SILENTLY BUT COUNTED: the
    ``paddle_tpu_kernel_fallbacks_total{kernel,reason}`` series records
    it (always-counted, like the serving stats counters), and the
    Selection that counted it reclaims its series on close — the same
    label-lifecycle contract GenerationServer.close follows.

The XLA path stays the numerics oracle: a kernel is only ever an
implementation swap, never a semantics change
(tests/test_serving_kernels.py pins greedy-decode bit-identity).
"""
from __future__ import annotations

from typing import Callable, Dict, List, Optional, Tuple

from ..observability import metrics as obs_metrics

__all__ = [
    "register_kernel",
    "kernels_mode",
    "kernels_armed",
    "interpret_mode",
    "Selection",
    "select",
    "FALLBACK_METRIC",
]

FALLBACK_METRIC = "paddle_tpu_kernel_fallbacks_total"

# always=True: fallback routing is a correctness-adjacent signal (an
# operator must be able to see that the armed kernel never ran), so it
# counts even with the metrics gate off — exported only when on
_M_FALLBACKS = obs_metrics.counter(
    FALLBACK_METRIC,
    "serving-kernel selections routed to the XLA oracle path",
    ("kernel", "reason"), always=True)


class _KernelDef:
    __slots__ = ("name", "supports", "build")

    def __init__(self, name, supports, build):
        self.name = name
        self.supports = supports
        self.build = build


_REGISTRY: Dict[str, _KernelDef] = {}


def register_kernel(name: str, supports: Callable[..., Optional[str]]):
    """Register `build(**ctx) -> callable` as the Pallas implementation
    of `name`; `supports(**ctx)` returns None when the context (shapes,
    dtypes, platform) is runnable and a short fallback reason otherwise.
    """

    def deco(build):
        _REGISTRY[name] = _KernelDef(name, supports, build)
        return build

    return deco


def kernels_mode() -> str:
    """The `serving_kernels` flag, normalized to auto/on/off."""
    from ..core.flags import get_flag

    v = str(get_flag("serving_kernels")).strip().lower()
    if v in ("1", "true", "yes", "on"):
        return "on"
    if v in ("0", "false", "no", "off"):
        return "off"
    return "auto"


def _platform() -> str:
    import jax

    return jax.default_backend()


def kernels_armed(platform: Optional[str] = None) -> bool:
    """Whether selection should even try the Pallas tier: "on" arms
    everywhere (CPU runs interpret mode), "auto" arms only on TPU —
    interpret mode is a correctness harness, not a fast path, so a CPU
    process under the default must keep the XLA oracle."""
    mode = kernels_mode()
    if mode == "off":
        return False
    if mode == "on":
        return True
    return (platform or _platform()) == "tpu"


def interpret_mode(platform: Optional[str] = None) -> bool:
    """Pallas interpret mode: anywhere the Mosaic TPU compiler is
    absent, i.e. every non-TPU backend."""
    return (platform or _platform()) != "tpu"


class Selection:
    """One consumer's kernel choices plus its fallback-series ledger.

    A builder (paged decoder, ParallelExecutor, moe_dense) makes its
    selections through one Selection so (a) introspection shows what
    actually runs (`chosen`: kernel name -> "pallas" or
    "xla:<reason>") and (b) `close()` reclaims exactly the
    {kernel,reason} series this consumer counted."""

    def __init__(self):
        self.chosen: Dict[str, str] = {}
        self._counted: List[Tuple[str, str]] = []

    def pick(self, name: str, **ctx):
        """-> the built kernel callable, or None for the XLA path.

        Disarmed (flag off, or auto on a non-TPU backend) returns None
        without counting — nothing fell back, the oracle was the plan.
        Armed but unsupported counts one fallback and returns None."""
        kdef = _REGISTRY.get(name)
        platform = ctx.pop("platform", None) or _platform()
        if kdef is None:
            raise KeyError(f"unknown serving kernel {name!r}; "
                           f"registered: {sorted(_REGISTRY)}")
        if not kernels_armed(platform):
            self.chosen[name] = "xla:disarmed"
            return None
        reason = kdef.supports(platform=platform, **ctx)
        if reason is not None:
            self.chosen[name] = f"xla:{reason}"
            self._counted.append((name, reason))
            _M_FALLBACKS.labels(kernel=name, reason=reason).inc()
            return None
        self.chosen[name] = "pallas"
        return kdef.build(platform=platform,
                          interpret=interpret_mode(platform), **ctx)

    def close(self):
        """Drop this consumer's fallback series (the {kernel,reason}
        children it incremented).  Safe to call twice; a series shared
        with a still-live consumer disappears from exports but keeps
        counting from zero if either increments again."""
        seen = set()
        for key in self._counted:
            if key in seen:
                continue
            seen.add(key)
            _M_FALLBACKS.remove(kernel=key[0], reason=key[1])
        self._counted = []


def select(name: str, **ctx):
    """One-off selection with no reclamation ledger (prefer a Selection
    for anything with a close() lifecycle)."""
    return Selection().pick(name, **ctx)
