"""Fused per-bucket optimizer update as one Pallas kernel.

The PR 9 overlap step already reduces gradients in same-dtype buckets
(one flat psum per bucket), but the update phase still runs the
program's per-parameter op chain — one tiny `sgd` dispatch per
parameter, each reading and writing its parameter through HBM with
kernel-launch overhead dwarfing the math.  This kernel applies the
whole bucket in ONE launch over the concatenated flat views:

    new_flat_params = flat_params - lr * flat_grads

tiled to the (8, 128) VPU grid.  The update is elementwise, so the
fusion is bit-identical to the per-parameter chain by construction
(same multiply, same subtract, f32 throughout — exactly what
ops/optimizer_ops.sgd computes under the f32-compute wrap); zero
padding to the tile boundary is sliced off before the views are split
back.

Eligibility is decided by ParallelExecutor (all update ops plain dense
`sgd` on f32 params sharing one learning-rate scalar per bucket, grads
fed straight from the bucket reduction); anything fancier — clipping
chains, mixed op types, sparse rows — falls back to the per-op chain
through kernels/registry.py ("fused_bucket_update"), counted.
"""
from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

from .registry import register_kernel

__all__ = ["fused_update_supports", "build_fused_bucket_update"]

_LANES = 128
_SUBLANES = 8
_TILE = _LANES * _SUBLANES


def fused_update_supports(*, numel: int, dtype: str = "float32",
                          structure: Optional[str] = None,
                          platform: str = "cpu", **_) -> Optional[str]:
    # `structure` is the executor's op-graph eligibility verdict
    # (op_mix, clipped_grads, lr_mismatch, ...): the update chain's
    # SHAPE ruled the fusion out before any per-bucket check, routed
    # through supports so it lands in the same counted-fallback series
    if structure:
        return str(structure)
    if dtype != "float32":
        return "dtype"
    if int(numel) < 1:
        return "empty_bucket"
    return None


def _sgd_kernel(p_ref, g_ref, lr_ref, o_ref):
    o_ref[...] = p_ref[...] - lr_ref[0, 0] * g_ref[...]


@register_kernel("fused_bucket_update", fused_update_supports)
def build_fused_bucket_update(*, numel: int, interpret: bool = False,
                              platform: str = "cpu", **_):
    """-> update(flat_params [numel] f32, flat_grads [numel] f32,
    lr scalar) -> new flat_params [numel] f32."""
    n = int(numel)
    pad = (-n) % _TILE
    rows = (n + pad) // _LANES
    grid = (rows // _SUBLANES,)

    def update(flat_p, flat_g, lr):
        p2 = jnp.pad(flat_p, (0, pad)).reshape(rows, _LANES)
        g2 = jnp.pad(flat_g, (0, pad)).reshape(rows, _LANES)
        lr2 = jnp.asarray(lr, jnp.float32).reshape(1, 1)
        out = pl.pallas_call(
            _sgd_kernel,
            grid=grid,
            in_specs=[
                pl.BlockSpec((_SUBLANES, _LANES), lambda i: (i, 0)),
                pl.BlockSpec((_SUBLANES, _LANES), lambda i: (i, 0)),
                pl.BlockSpec((1, 1), lambda i: (0, 0)),
            ],
            out_specs=pl.BlockSpec((_SUBLANES, _LANES),
                                   lambda i: (i, 0)),
            out_shape=jax.ShapeDtypeStruct((rows, _LANES),
                                           jnp.float32),
            interpret=interpret,
        )(p2, g2, lr2)
        return out.reshape(-1)[:n]

    return update
