"""Hand-written Pallas TPU kernels for the hot ops.

The reference hand-writes CUDA for its hot paths (paddle/cuda/src/hl_*.cu,
operators/math/*.cu); here XLA fusion covers most of that ground, and Pallas
covers what fusion cannot: the attention inner loop (flash attention — the
reference has no attention kernel at all, SURVEY.md §5.7) where materializing
the [q, k] score matrix in HBM is the bandwidth bottleneck.
"""
from .flash_attention import flash_attention, flash_attention_reference  # noqa: F401
