"""Hand-written Pallas TPU kernels for the hot ops.

The reference hand-writes CUDA for its hot paths (paddle/cuda/src/hl_*.cu,
operators/math/*.cu); here XLA fusion covers most of that ground, and Pallas
covers what fusion cannot: the attention inner loop (flash attention — the
reference has no attention kernel at all, SURVEY.md §5.7) where materializing
the [q, k] score matrix in HBM is the bandwidth bottleneck.
The serving tier (registry.py) extends that to the static analyzer's
memory-bound worklist: paged-attention decode with in-kernel block-table
reads and fused dequant, fused MoE gate+dispatch, and the fused per-bucket
optimizer update — all selected behind the `serving_kernels` flag with
per-op fallback to the XLA oracle path (docs/performance.md).
"""
from .flash_attention import flash_attention, flash_attention_reference  # noqa: F401
from .registry import (  # noqa: F401
    FALLBACK_METRIC,
    Selection,
    interpret_mode,
    kernels_armed,
    kernels_mode,
    register_kernel,
    select,
)
from .paged_attention import build_paged_attention, paged_attention_supports  # noqa: F401
from .moe_dispatch import build_moe_gate_dispatch, moe_dispatch_supports  # noqa: F401
from .fused_update import build_fused_bucket_update, fused_update_supports  # noqa: F401
