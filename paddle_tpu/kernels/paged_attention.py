"""Paged-attention decode as a Pallas TPU kernel (vLLM-style).

The paged decoder (models/transformer.build_lm_paged_decoder) is the
serving hot path and the top entry on the static analyzer's
memory-bound worklist: its XLA lowering gathers K/V through the block
table into a logical-order [S, ctx, d] copy in HBM every tick, and
quantized pools additionally pay a full dequantization round-trip on
that copy.  This kernel reads K/V blocks DIRECTLY through the block
table — the table rides the scalar-prefetch lane, so each grid step's
BlockSpec index map addresses one physical pool block and Pallas
streams exactly the blocks a slot owns into VMEM, dequantizing in-lane
(bf16 cast / int8 per-(layer, block) scale) on the way.  No
logical-order copy of the pool ever exists in HBM.

Grid = (slots, max_blocks_per_seq), block index innermost so one
slot's K/V blocks accumulate into a VMEM scratch of the logical
context; the last block step runs the attention math for that slot.
The math is POSITION-FOR-POSITION the oracle's (gather + QK^T +
-inf mask + jax.nn.softmax + att@V, f32 accumulation), which is what
makes greedy decode through this kernel bit-identical to the XLA paged
path — tests/test_serving_kernels.py pins it for fp32/bf16/int8 under
Pallas interpret mode on CPU.

`window > 1` is the teacher-forced multi-position variant: the same
kernel body scores a [W, ctx] tile per slot (causal within the window
via the position offsets), so speculative-decoding verification and
chunked prefill ride the same kernel as single-token decode.

Selection and fallback accounting live in kernels/registry.py
("paged_attention_decode"); unsupported shape/dtype/platform
combinations route back to the oracle, counted.
"""
from __future__ import annotations

import functools
import math
from typing import Optional

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

try:  # pallas TPU backend (absent on some CPU-only builds)
    from jax.experimental.pallas import tpu as pltpu
except ImportError:  # pragma: no cover
    pltpu = None

from .registry import register_kernel

__all__ = ["paged_attention_supports", "build_paged_attention"]

# VMEM budget for the per-slot K+V logical-context scratch: past this
# the context must be tiled with an online softmax, which trades away
# the oracle's exact math — out of scope for the serving tier, so the
# registry falls back instead
_SCRATCH_BUDGET_BYTES = 8 * 1024 * 1024


def paged_attention_supports(*, d_model: int, n_heads: int,
                             block_size: int, max_blocks_per_seq: int,
                             kv_dtype: str, window: int = 1,
                             platform: str = "cpu",
                             **_) -> Optional[str]:
    """None when the decode shape runs on the Pallas path, else a short
    fallback reason (the {kernel,reason} counter label)."""
    if kv_dtype not in ("fp32", "bf16", "int8"):
        return "kv_dtype"
    if d_model % n_heads:
        return "head_split"
    ctx = max_blocks_per_seq * block_size
    if 2 * ctx * d_model * 4 > _SCRATCH_BUDGET_BYTES:
        return "vmem_scratch"
    if int(window) < 1:
        return "window"
    if platform == "tpu":
        # Mosaic tiling: last dim on the 128-lane grid, K/V block rows
        # on the 8-sublane grid; the per-head slice must stay
        # lane-aligned
        if d_model % 128:
            return "lane_misaligned"
        if (d_model // n_heads) % 128:
            return "head_dim_misaligned"
        if block_size % 8:
            return "sublane_misaligned"
    if pltpu is None:
        return "no_pallas_tpu"
    return None


def _decode_kernel(tables_ref, pos_ref, q_ref, kv_refs, vv_refs,
                   o_ref, k_s, v_s, *, nb, bs, n_heads, d_head, scale,
                   kv_dtype):
    """Grid step (s, i): dequantize-copy pool block `tables[s, i]` into
    the logical-context scratch; at the slot's last block, run the
    oracle's attention math on the assembled [ctx, d] tiles.

    `kv_refs`/`vv_refs` mirror the pool pytree: a bare block ref for
    fp32/bf16, a (payload, scale) ref pair for int8."""
    s, i = pl.program_id(0), pl.program_id(1)
    ctx_len = nb * bs

    if kv_dtype == "int8":
        kq_ref, ks_ref = kv_refs
        vq_ref, vs_ref = vv_refs
        k_s[pl.ds(i * bs, bs), :] = (kq_ref[0, 0].astype(jnp.float32)
                                     * ks_ref[0, 0])
        v_s[pl.ds(i * bs, bs), :] = (vq_ref[0, 0].astype(jnp.float32)
                                     * vs_ref[0, 0])
    else:
        k_s[pl.ds(i * bs, bs), :] = kv_refs[0, 0].astype(jnp.float32)
        v_s[pl.ds(i * bs, bs), :] = vv_refs[0, 0].astype(jnp.float32)

    @pl.when(i == nb - 1)
    def _attend():
        # the math below is TOKEN-FOR-TOKEN the oracle's gather block
        # (same einsum contractions, same mask/softmax order) — that,
        # not just closeness, is what the bit-identity pins rely on
        w_n = q_ref.shape[1]
        kh = k_s[...].reshape(ctx_len, n_heads, d_head)
        vh = v_s[...].reshape(ctx_len, n_heads, d_head)
        if w_n == 1:
            # single-token decode: mirror step()'s windowless einsums —
            # a size-1 q-dim contraction is NOT bitwise the same, so
            # the branch is static on the block shape
            qh = q_ref[0, 0].astype(jnp.float32).reshape(n_heads,
                                                         d_head)
            sc = jnp.einsum("hd,shd->hs", qh, kh) * scale
            cols = jax.lax.broadcasted_iota(jnp.int32, (1, ctx_len), 1)
            keep = (cols <= pos_ref[s])[0]
            sc = jnp.where(keep[None, :], sc, -jnp.inf)
            w_att = jax.nn.softmax(sc, axis=-1)
            ctxh = jnp.einsum("hs,shd->hd", w_att, vh)
            o_ref[0, 0] = ctxh.reshape(n_heads * d_head)
        else:
            qh = q_ref[0].astype(jnp.float32).reshape(
                w_n, n_heads, d_head)
            sc = jnp.einsum("qhd,shd->qhs", qh, kh) * scale
            # absolute position of window row w is pos[s] + w; row w
            # attends to logical positions <= it, matching
            # step_window's teacher-forced causal mask
            cols = jax.lax.broadcasted_iota(
                jnp.int32, (w_n, ctx_len), 1)
            rows = jax.lax.broadcasted_iota(
                jnp.int32, (w_n, ctx_len), 0)
            keep = cols <= pos_ref[s] + rows
            sc = jnp.where(keep[:, None, :], sc, -jnp.inf)
            w_att = jax.nn.softmax(sc, axis=-1)
            ctxh = jnp.einsum("qhs,shd->qhd", w_att, vh)
            o_ref[0] = ctxh.reshape(w_n, n_heads * d_head)


@register_kernel("paged_attention_decode", paged_attention_supports)
def build_paged_attention(*, d_model: int, n_heads: int,
                          block_size: int, max_blocks_per_seq: int,
                          kv_dtype: str, window: int = 1,
                          interpret: bool = False, platform: str = "cpu",
                          **_):
    """-> attend(q, pool_k, pool_v, tables, positions, layer) where
    q is [S, W, d_model] f32 (the window W is taken from q's shape at
    trace time — the single-token step passes W=1, speculative verify
    its draft window), pools are the paged decoder's layer-major pool
    pytrees, and the result is the pre-output-projection context
    [S, W, d_model] f32 — a drop-in for the oracle's
    gather/einsum/softmax block."""
    nb, bs = int(max_blocks_per_seq), int(block_size)
    d_head = d_model // n_heads
    scale = 1.0 / math.sqrt(d_head)

    kern = functools.partial(
        _decode_kernel, nb=nb, bs=bs, n_heads=n_heads, d_head=d_head,
        scale=scale, kv_dtype=kv_dtype)

    def _pool_specs(layer):
        # one physical pool block per grid step, addressed THROUGH the
        # prefetched table — the kernel never sees a logical-order copy
        def blk(s, i, tab, pos):
            return (layer, tab[s, i], 0, 0)

        if kv_dtype == "int8":
            def scl(s, i, tab, pos):
                return (layer, tab[s, i])

            return (pl.BlockSpec((1, 1, bs, d_model), blk),
                    pl.BlockSpec((1, 1), scl))
        return pl.BlockSpec((1, 1, bs, d_model), blk)

    def attend(q, pool_k, pool_v, tables, positions, layer):
        s_n, w_n = q.shape[0], q.shape[1]
        grid_spec = pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=2,
            grid=(s_n, nb),
            in_specs=[
                pl.BlockSpec((1, w_n, d_model),
                             lambda s, i, tab, pos: (s, 0, 0)),
                _pool_specs(layer),
                _pool_specs(layer),
            ],
            out_specs=pl.BlockSpec((1, w_n, d_model),
                                   lambda s, i, tab, pos: (s, 0, 0)),
            scratch_shapes=[
                pltpu.VMEM((nb * bs, d_model), jnp.float32),
                pltpu.VMEM((nb * bs, d_model), jnp.float32),
            ],
        )
        return pl.pallas_call(
            kern, grid_spec=grid_spec,
            out_shape=jax.ShapeDtypeStruct((s_n, w_n, d_model),
                                           jnp.float32),
            interpret=interpret,
        )(tables, positions, q, pool_k, pool_v)

    return attend
