"""Transformer model family (decoder-only LM + encoder-decoder translator).

The reference predates the transformer as a packaged model: attention is
composed from primitive ops (/root/reference/python/paddle/v2/fluid/nets.py:162-219
scaled_dot_product_attention) and its NMT book model is a plain seq2seq
without attention (/root/reference/python/paddle/v2/fluid/tests/book/
test_machine_translation.py:54-121).  The rebuild promotes the transformer
to a first-class model family because it is the TPU-native long-sequence
answer to the reference's LoD/DynamicRNN machinery (SURVEY.md section 5.7):
static shapes + masking, flash-attention Pallas kernel on the hot path
(kernels/flash_attention.py), and ring/Ulysses sequence parallelism
(parallel/ring_attention.py) for contexts that exceed one chip.

All blocks are pre-LN (LN -> sublayer -> residual add), which keeps
activations bounded for bf16 training on the MXU.
"""
from __future__ import annotations

import contextlib

from .. import layers, nets
from ..core.flags import get_flag
from ..core.framework import pipeline_stage
from ..initializer import NormalInitializer

__all__ = [
    "multi_head_attention",
    "positionwise_ffn",
    "transformer_encoder",
    "transformer_decoder",
    "transformer_lm",
    "transformer_translate",
    "build_lm_generator",
    "build_lm_kv_decoder",
    "build_lm_paged_decoder",
    "build_translate_generator",
    "build_lm_beam_search",
]


def _proj(x, size, name=None):
    """Linear projection over the feature axis of a [b, s, d] tensor."""
    return layers.fc(input=x, size=size, num_flatten_dims=2,
                     bias_attr=True, act=None, name=name)


def multi_head_attention(queries, keys, values, d_model, n_heads,
                         causal=False, dropout_rate=0.0, is_test=False):
    """Projected multi-head attention on [b, s, d] tensors.

    Projections + nets.scaled_dot_product_attention (which lowers to the
    Pallas flash-attention kernel whenever there is no attention-weight
    dropout); queries and keys/values may have different sequence lengths
    (cross attention).
    """
    q = _proj(queries, d_model)
    k = _proj(keys, d_model)
    v = _proj(values, d_model)
    ctx = nets.scaled_dot_product_attention(
        q, k, v, num_heads=n_heads, dropout_rate=dropout_rate,
        causal=causal, is_test=is_test)
    return _proj(ctx, d_model)


def positionwise_ffn(x, d_model, d_inner, dropout_rate=0.0, is_test=False):
    hidden = layers.fc(input=x, size=d_inner, num_flatten_dims=2,
                       act="relu")
    if dropout_rate:
        hidden = layers.dropout(hidden, dropout_prob=dropout_rate,
                                is_test=is_test)
    return layers.fc(input=hidden, size=d_model, num_flatten_dims=2)


def _pre_ln(x):
    return layers.layer_norm(x, begin_norm_axis=2)


def _embed(ids, vocab_size, d_model, max_len, dropout_rate, is_test):
    """Token embedding + learned positional embedding.

    ids: [b, s] int64.  Positions use a learned table sized to the static
    sequence length (static shapes are the TPU answer to the reference's
    LoD offsets — SURVEY.md section 5.7).
    """
    seq = int(ids.shape[1])
    if seq > max_len:
        raise ValueError(f"sequence length {seq} exceeds max_len {max_len}")
    # no fixed param names: two models in one program must not silently
    # share tables (Block.create_parameter overwrites same-named vars)
    emb = layers.embedding(
        ids, size=[vocab_size, d_model],
        param_attr={"initializer": NormalInitializer(0.0, 0.02)})
    # position table sized to max_len so checkpoints restore across
    # sequence lengths; the current static length slices into it
    pos_table = layers.create_parameter(
        shape=[max_len, d_model], dtype=emb.dtype,
        default_initializer=NormalInitializer(0.0, 0.02))
    pos = layers.slice(pos_table, axes=[0], starts=[0], ends=[seq])
    x = layers.elementwise_add(emb, pos, axis=1)
    if dropout_rate:
        x = layers.dropout(x, dropout_prob=dropout_rate, is_test=is_test)
    return x


def _encoder_block(x, d_model, n_heads, d_inner, dropout_rate, is_test):
    ln_x = _pre_ln(x)
    a = multi_head_attention(ln_x, ln_x, ln_x, d_model, n_heads,
                             causal=False,
                             dropout_rate=dropout_rate, is_test=is_test)
    x = layers.elementwise_add(x, a)
    f = positionwise_ffn(_pre_ln(x), d_model, d_inner, dropout_rate, is_test)
    return layers.elementwise_add(x, f)


def _decoder_block(x, enc_out, d_model, n_heads, d_inner, dropout_rate,
                   is_test):
    ln_x = _pre_ln(x)
    a = multi_head_attention(ln_x, ln_x, ln_x, d_model, n_heads,
                             causal=True, dropout_rate=dropout_rate,
                             is_test=is_test)
    x = layers.elementwise_add(x, a)
    if enc_out is not None:
        c = multi_head_attention(_pre_ln(x), enc_out, enc_out, d_model,
                                 n_heads, causal=False,
                                 dropout_rate=dropout_rate, is_test=is_test)
        x = layers.elementwise_add(x, c)
    f = positionwise_ffn(_pre_ln(x), d_model, d_inner, dropout_rate, is_test)
    return layers.elementwise_add(x, f)


def transformer_encoder(src_ids, vocab_size, d_model=256, n_heads=4,
                        n_layers=2, d_inner=None, max_len=2048,
                        dropout_rate=0.0, is_test=False, remat=None):
    """Bidirectional encoder over [b, s] token ids -> [b, s, d_model].

    `remat=True` wraps each block in layers.recompute (jax.checkpoint):
    the block's internal activations are re-run in backward instead of
    living in HBM — the standard bytes-for-FLOPs trade on a
    memory-bound training step.  remat=None defers to the `remat` flag
    (PADDLE_TPU_REMAT, build-time)."""
    d_inner = d_inner or 4 * d_model
    if remat is None:
        remat = bool(get_flag("remat"))
    x = _embed(src_ids, vocab_size, d_model, max_len, dropout_rate,
               is_test)
    for _ in range(n_layers):
        if remat:
            x = layers.recompute(
                lambda x=x: _encoder_block(x, d_model, n_heads, d_inner,
                                           dropout_rate, is_test))
        else:
            x = _encoder_block(x, d_model, n_heads, d_inner, dropout_rate,
                               is_test)
    return _pre_ln(x)


def transformer_decoder(tgt_ids, enc_out, vocab_size, d_model=256,
                        n_heads=4, n_layers=2, d_inner=None, max_len=2048,
                        dropout_rate=0.0, is_test=False, remat=None,
                        pipeline_stages=None):
    """Causal decoder ([b, t] ids, optional [b, s, d] memory) -> [b, t, d].

    `pipeline_stages=S` annotates the block stack with
    `fluid.pipeline_stage` (n_layers/S consecutive blocks per stage) so
    the SAME program runs serially or as a GPipe pipeline under
    parallel.PipelineExecutor over a 'pp' mesh axis — the DSL-reachable
    counterpart of the reference's per-layer device placement
    (/root/reference/paddle/gserver/gradientmachines/ParallelNeuralNetwork.h).
    Embedding stays outside the trunk (the usual GPipe decomposition);
    the final layer_norm lands in the post section.
    """
    d_inner = d_inner or 4 * d_model
    if remat is None:
        # the `remat` flag never overrides a pipeline build (the GPipe
        # schedule already recomputes per-microbatch)
        remat = bool(get_flag("remat")) and not pipeline_stages
    if pipeline_stages:
        if n_layers % pipeline_stages:
            raise ValueError(
                f"n_layers {n_layers} must be a multiple of "
                f"pipeline_stages {pipeline_stages}")
        if remat:
            raise NotImplementedError(
                "remat inside pipeline stages is redundant: the GPipe "
                "schedule already recomputes per-microbatch")
    x = _embed(tgt_ids, vocab_size, d_model, max_len, dropout_rate,
               is_test)
    for i in range(n_layers):
        stage = (pipeline_stage(i * pipeline_stages // n_layers)
                 if pipeline_stages else contextlib.nullcontext())
        with stage:
            if remat:
                x = layers.recompute(
                    lambda x=x: _decoder_block(x, enc_out, d_model,
                                               n_heads, d_inner,
                                               dropout_rate, is_test))
            else:
                x = _decoder_block(x, enc_out, d_model, n_heads, d_inner,
                                   dropout_rate, is_test)
    return _pre_ln(x)


def transformer_lm(ids, vocab_size, d_model=256, n_heads=4, n_layers=2,
                   d_inner=None, max_len=2048, dropout_rate=0.0,
                   is_test=False, return_logits=False,
                   pipeline_stages=None):
    """Decoder-only causal language model: [b, s] ids -> [b, s, vocab]
    next-token softmax probabilities (raw logits with
    `return_logits=True`; `pipeline_stages` as in transformer_decoder)."""
    h = transformer_decoder(ids, None, vocab_size, d_model, n_heads,
                            n_layers, d_inner, max_len, dropout_rate,
                            is_test, pipeline_stages=pipeline_stages)
    logits = layers.fc(input=h, size=vocab_size, num_flatten_dims=2)
    if return_logits:
        return logits
    return layers.softmax(logits)


def transformer_translate(src_ids, tgt_ids, src_vocab, tgt_vocab,
                          d_model=256, n_heads=4, n_layers=2, d_inner=None,
                          max_len=2048, dropout_rate=0.0, is_test=False,
                          return_logits=False, remat=None):
    """Encoder-decoder translation model -> [b, t, tgt_vocab] softmax
    (or raw logits with `return_logits=True` — training should feed
    those to softmax_with_cross_entropy so the [b*t, vocab] probability
    tensor is never materialized in HBM: at vocab 30k that tensor plus
    its backward dominates the step's memory traffic)."""
    enc = transformer_encoder(src_ids, src_vocab, d_model, n_heads,
                              n_layers, d_inner, max_len, dropout_rate,
                              is_test, remat=remat)
    dec = transformer_decoder(tgt_ids, enc, tgt_vocab, d_model, n_heads,
                              n_layers, d_inner, max_len, dropout_rate,
                              is_test, remat=remat)
    logits = layers.fc(input=dec, size=tgt_vocab, num_flatten_dims=2)
    if return_logits:
        return logits
    return layers.softmax(logits)


def build_lm_generator(vocab_size, max_len, d_model=256, n_heads=4,
                       n_layers=2, d_inner=None):
    """Autoregressive generation for the decoder-only LM, fully on-device.

    Builds the LM Program once at width `max_len`, bridges it to a pure
    jax function (core/executor.program_to_fn), and wraps the decode loop
    in `jax.lax.fori_loop` inside ONE jit — the whole generation runs as a
    single XLA computation (no per-token host round-trips; the causal
    mask makes positions past the cursor inert, so the fixed-width
    forward is exact).  The reference's analogue is host-side While +
    beam_search ops over LoD (book/08 decode); this is the static-shape
    TPU counterpart for the transformer family.

    Returns (startup_program, generate) where
      generate(states, prompt_ids [B, P], num_steps,
               temperature=0.0, seed=0) -> ids [B, max_len]
    with greedy argmax at temperature 0 and softmax sampling otherwise.
    `states` is the param dict from the startup program (e.g. via
    `Parameters` or `_init_states`-style scope reads), so generation uses
    the same trained values as training.
    """
    import jax
    import jax.numpy as jnp

    from ..core.framework import Program, program_guard
    from ..core.executor import program_to_fn

    main, startup = Program(), Program()
    with program_guard(main, startup):
        ids_in = layers.data(name="gen_ids", shape=[max_len],
                             dtype="int64")
        probs = transformer_lm(ids_in, vocab_size, d_model=d_model,
                               n_heads=n_heads, n_layers=n_layers,
                               d_inner=d_inner, max_len=max_len,
                               is_test=True)
    fn = program_to_fn(main, ["gen_ids"], [probs.name])

    # ONE jit for the builder's lifetime: defined here (not inside
    # generate) so repeated generate() calls hit the executable cache —
    # a per-call closure would re-trace+compile the whole decode loop
    # every time.  p/num_steps/temperature are static (re-trace only per
    # distinct shape/temperature).
    import functools

    @functools.partial(jax.jit,
                       static_argnames=("p", "num_steps", "temperature"))
    def _run(ids0, states, key, p, num_steps, temperature):
        def body(i, carry):
            ids, k = carry
            fetches, _ = fn({"gen_ids": ids}, states, k)
            pr = fetches[probs.name]              # [B, max_len, V]
            step_p = jax.lax.dynamic_slice_in_dim(
                pr, i - 1, 1, axis=1)[:, 0]       # [B, V] at cursor-1
            if temperature and temperature > 0.0:
                k, sub = jax.random.split(k)
                logits = jnp.log(step_p + 1e-9) / temperature
                nxt = jax.random.categorical(sub, logits, axis=-1)
            else:
                nxt = jnp.argmax(step_p, axis=-1)
            ids = jax.lax.dynamic_update_slice(
                ids, nxt[:, None].astype(jnp.int32), (0, i))
            return ids, k

        ids, _ = jax.lax.fori_loop(p, p + num_steps, body, (ids0, key))
        return ids

    def generate(states, prompt_ids, num_steps, temperature=0.0, seed=0):
        prompt_ids = jnp.asarray(prompt_ids, jnp.int32)
        b, p = prompt_ids.shape
        assert p + num_steps <= max_len, "prompt + steps exceeds max_len"
        ids0 = jnp.zeros((b, max_len), jnp.int32)
        ids0 = jax.lax.dynamic_update_slice(ids0, prompt_ids, (0, 0))
        key = jax.random.key(seed)
        return _run(ids0, states, key, p, int(num_steps),
                    float(temperature))

    generate.state_names = list(fn.state_in_names)
    return startup, generate


def _lm_param_structure(vocab_size, max_len, d_model, n_heads, n_layers,
                        d_inner):
    """Build the LM Program once and extract its parameter names
    STRUCTURALLY (op walk, creation order) so a hand-rolled incremental
    decoder computes over the SAME trained values as the Program path.

    Returns (startup, param_names, tok_emb, pos_tab, lns, weights,
    biases); shared by build_lm_kv_decoder (dense cache) and
    build_lm_paged_decoder (block-table cache)."""
    from ..core.framework import Program, program_guard

    main, startup = Program(), Program()
    with program_guard(main, startup):
        ids_in = layers.data(name="gen_ids", shape=[max_len],
                             dtype="int64")
        transformer_lm(ids_in, vocab_size, d_model=d_model,
                       n_heads=n_heads, n_layers=n_layers,
                       d_inner=d_inner, max_len=max_len, is_test=True)

    blk = main.global_block()
    params = {v.name for v in blk.all_parameters()}
    tok_emb = pos_tab = None
    lns, weights, biases = [], [], []
    for op in blk.ops:
        if op.type == "lookup_table":
            tok_emb = op.inputs["W"][0]
        elif op.type == "slice" and op.inputs["Input"][0] in params:
            pos_tab = op.inputs["Input"][0]
        elif op.type == "layer_norm":
            lns.append((op.inputs["Scale"][0], op.inputs["Bias"][0]))
        elif op.type == "mul":
            weights.append(op.inputs["Y"][0])
        elif op.type == "elementwise_add":
            y = op.inputs.get("Y", [None])[0]
            if y in params and len(biases) < len(weights):
                biases.append(y)
    assert tok_emb and pos_tab, "unexpected LM program structure"
    assert len(weights) == 6 * n_layers + 1, (len(weights), n_layers)
    assert len(lns) == 2 * n_layers + 1
    assert len(biases) == len(weights)
    shapes = {v.name: tuple(int(d) for d in v.shape)
              for v in blk.all_parameters()}
    return startup, shapes, tok_emb, pos_tab, lns, weights, biases


def build_lm_kv_decoder(vocab_size, max_len, d_model=256, n_heads=4,
                        n_layers=2, d_inner=None):
    """Incremental (KV-cache) generation for the decoder-only LM.

    `build_lm_generator` re-runs the full fixed-width forward per token
    (O(L) matmuls per step).  This fast path keeps per-layer K/V caches
    and computes ONE token per step — the standard serving decode loop —
    as a hand-rolled jax function over the SAME trained parameters:
    the LM Program is built once, its parameter names are extracted
    structurally (op walk, creation order), and the incremental math
    mirrors nets.scaled_dot_product_attention's feature-major head split.
    Token-identical greedy decode vs the full forward is pinned by
    tests/test_transformer.py.

    Returns (startup_program, generate) with the same signature as
    `build_lm_generator`.
    """
    import math

    import jax
    import jax.numpy as jnp

    d_inner = d_inner or 4 * d_model
    d_head = d_model // n_heads

    startup, shapes, tok_emb, pos_tab, lns, weights, biases = (
        _lm_param_structure(vocab_size, max_len, d_model, n_heads,
                            n_layers, d_inner))

    import functools

    scale = 1.0 / math.sqrt(d_head)

    # one jit per builder (executable cache survives across generate()
    # calls; p/num_steps/temperature are static)
    @functools.partial(jax.jit,
                       static_argnames=("p", "num_steps", "temperature"))
    def _run(ids0, caches0, g, key, p, num_steps, temperature):
        # params enter as ARGUMENTS (not jit-closure constants: baking
        # the weights into the executable makes XLA treat every matmul
        # operand as a literal — measured 10x slower on the chip)
        b = ids0.shape[0]

        def W(i):
            return g[weights[i]], g[biases[i]]

        def ln(x, i):
            s, b = g[lns[i][0]], g[lns[i][1]]
            mu = x.mean(-1, keepdims=True)
            var = ((x - mu) ** 2).mean(-1, keepdims=True)
            return (x - mu) / jnp.sqrt(var + 1e-5) * s + b

        def body(i, carry):
            ids, caches, k = carry
            tok = jax.lax.dynamic_slice_in_dim(ids, i, 1, 1)[:, 0]
            x = g[tok_emb][tok] + g[pos_tab][i]        # [B, D]
            new_caches = []
            for l in range(n_layers):
                h = ln(x, 2 * l)
                wq, bq = W(6 * l + 0)
                wk, bk = W(6 * l + 1)
                wv, bv = W(6 * l + 2)
                wo, bo = W(6 * l + 3)
                q = h @ wq + bq
                kk = h @ wk + bk
                vv = h @ wv + bv
                ck, cv = caches[l]
                ck = jax.lax.dynamic_update_slice(
                    ck, kk[:, None, :], (0, i, 0))
                cv = jax.lax.dynamic_update_slice(
                    cv, vv[:, None, :], (0, i, 0))
                new_caches.append((ck, cv))
                qh = q.reshape(b, n_heads, d_head)
                kh = ck.reshape(b, max_len, n_heads, d_head)
                vh = cv.reshape(b, max_len, n_heads, d_head)
                sc = jnp.einsum("bhd,bshd->bhs", qh, kh) * scale
                sc = jnp.where(
                    (jnp.arange(max_len) <= i)[None, None, :],
                    sc, -jnp.inf)
                w_att = jax.nn.softmax(sc, axis=-1)
                ctxh = jnp.einsum("bhs,bshd->bhd", w_att, vh)
                x = x + (ctxh.reshape(b, d_model) @ wo + bo)
                h2 = ln(x, 2 * l + 1)
                w1, b1 = W(6 * l + 4)
                w2, b2 = W(6 * l + 5)
                x = x + (jax.nn.relu(h2 @ w1 + b1) @ w2 + b2)
            xf = ln(x, 2 * n_layers)
            wf, bf = W(6 * n_layers)
            logits = xf @ wf + bf                       # [B, V]
            if temperature and temperature > 0.0:
                k, sub = jax.random.split(k)
                nxt = jax.random.categorical(
                    sub, logits / temperature, axis=-1)
            else:
                nxt = jnp.argmax(logits, axis=-1)
            # past the prompt, the model's token becomes position i+1
            keep_prompt = (i + 1) < p
            cur = jax.lax.dynamic_slice_in_dim(ids, i + 1, 1, 1)[:, 0]
            wr = jnp.where(keep_prompt, cur, nxt.astype(jnp.int32))
            ids = jax.lax.dynamic_update_slice(
                ids, wr[:, None], (0, i + 1))
            return ids, tuple(new_caches), k

        ids, _, _ = jax.lax.fori_loop(0, p + num_steps - 1, body,
                                      (ids0, caches0, key))
        return ids

    def generate(states, prompt_ids, num_steps, temperature=0.0, seed=0):
        g_in = {n: jnp.asarray(v) for n, v in states.items()}
        prompt_ids = jnp.asarray(prompt_ids, jnp.int32)
        b, p = prompt_ids.shape
        assert p + num_steps <= max_len
        ids0 = jnp.zeros((b, max_len), jnp.int32)
        ids0 = jax.lax.dynamic_update_slice(ids0, prompt_ids, (0, 0))
        caches0 = tuple(
            (jnp.zeros((b, max_len, d_model)),
             jnp.zeros((b, max_len, d_model))) for _ in range(n_layers))
        return _run(ids0, caches0, g_in, jax.random.key(seed), p,
                    int(num_steps), float(temperature))

    generate.state_names = sorted(shapes)
    generate.state_shapes = shapes
    return startup, generate


def build_lm_paged_decoder(vocab_size, block_size, max_blocks_per_seq,
                           d_model=256, n_heads=4, n_layers=2,
                           d_inner=None, kv_dtype=None):
    """Paged-attention decode step for the decoder-only LM.

    `build_lm_kv_decoder` owns a dense per-sequence cache
    ([B, max_len, d]) whose lifetime is one generate() call — fine for
    a closed batch, wrong for serving: a batch slot holds max_len worth
    of HBM for its whole life and a new request cannot join a running
    loop.  This builder produces the vLLM-style alternative: K/V live
    in fixed-size BLOCKS inside one shared pool
    ([n_layers, num_blocks, block_size, d_model]) and each sequence
    owns an ordered block table mapping its logical positions onto pool
    blocks.  Attention gathers through the table, so the kernel sees
    exactly the values a dense cache would hold — per-slot math is
    independent of which physical blocks a sequence happens to own and
    of what other slots compute, which is what makes continuously-
    batched decode bit-identical to running the same prompt solo
    (tests/test_generation_serving.py pins this).

    Unlike the closed-batch builders this returns a SINGLE decode step
    (one token per active slot per call), because the serving scheduler
    (serving/generation.py GenerationServer) must get control back
    between steps to admit/evict sequences; the whole step is one jit
    with the pool buffers donated, so a tick is one dispatch and the
    pool updates in place on device.

    Returns (startup_program, decoder):
      decoder.step(states, pool_k, pool_v, tables, positions, tokens,
                   seeds, temps, active)
          -> (next_tokens [S] int32, pool_k, pool_v)
        tables    [S, max_blocks_per_seq] int32 pool-block ids (unused
                  tail entries must point at a valid block, e.g. the
                  pool's reserved null block — they are masked out)
        positions [S] int32 logical cursor: `tokens[s]` is the token AT
                  this position; the step writes its K/V there and
                  returns the model's prediction for position+1
        seeds     [S] uint32 per-sequence sampling seed (the PRNG is
                  fold_in(key(seed), position): stateless, so a retried
                  / re-scheduled sequence resamples identically)
        temps     [S] float32, 0 = greedy argmax
        active    [S] bool; inactive slots write into the null block
                  and their outputs are meaningless
      decoder.init_pool(num_blocks) -> (pool_k, pool_v) zero blocks
      decoder.state_names — parameter names, same trained values as the
      Program path (shared structural extraction with the dense
      decoder).

    `kv_dtype` selects the POOL's storage precision (the
    quantize-on-write / dequantize-on-gather side of docs/serving.md
    "KV quantization"; compute stays float32):
      * "fp32" (default): plain float32 blocks;
      * "bf16": blocks stored bfloat16 (half the resident bytes,
        ~mantissa-rounding error on attention values);
      * "int8": blocks stored int8 with ONE float32 scale per
        (layer, block).  A write re-quantizes the whole target block
        under the new running max (blocks fill strictly in position
        order, so the valid region is exactly the offsets below the
        cursor) — a quarter of the resident bytes.
    None reads the `serving_kv_dtype` flag (PADDLE_TPU_SERVING_KV_DTYPE)
    and falls back to fp32.  Pools for bf16/int8 are pytrees the caller
    treats opaquely; `decoder.bytes_per_block` reports the resident
    K+V bytes per block for sizing/telemetry.

    `decoder.step_window(states, pool_k, pool_v, tables, positions,
    tokens [S, W], seeds, temps, n_valid [S]) -> (preds [S, W], pools)`
    is the teacher-forced MULTI-position step: slot s processes
    positions `positions[s] .. positions[s]+n_valid[s]-1` with the
    given tokens in ONE dispatch (causal within the window), writing
    each position's K/V and returning each position's next-token
    prediction.  It is what chunked prefill and speculative-decoding
    verification (serving/generation.py) run; window rows past
    n_valid write into the null block and return garbage.
    """
    import functools
    import math

    import jax
    import jax.numpy as jnp

    from ..core import flags as core_flags

    d_inner = d_inner or 4 * d_model
    d_head = d_model // n_heads
    nb, bs = int(max_blocks_per_seq), int(block_size)
    max_len = nb * bs
    if kv_dtype is None:
        kv_dtype = core_flags.get_flag("serving_kv_dtype") or "fp32"
    kv_dtype = {"float32": "fp32", "bfloat16": "bf16"}.get(
        str(kv_dtype).lower(), str(kv_dtype).lower())
    if kv_dtype not in ("fp32", "bf16", "int8"):
        raise ValueError(
            f"kv_dtype {kv_dtype!r} not in ('fp32', 'bf16', 'int8')")

    # serving-kernel selection, read at BUILD time like kv_dtype: when
    # armed and supported, attention reads K/V straight through the
    # block table inside the Pallas kernel (fused dequant, no
    # logical-order gather copy); otherwise the XLA gather below stays
    # the oracle (docs/performance.md "Serving kernels")
    from ..kernels import registry as _kernel_registry

    kernel_selection = _kernel_registry.Selection()
    _attend = kernel_selection.pick(
        "paged_attention_decode", d_model=d_model, n_heads=n_heads,
        block_size=int(block_size),
        max_blocks_per_seq=int(max_blocks_per_seq), kv_dtype=kv_dtype)

    startup, shapes, tok_emb, pos_tab, lns, weights, biases = (
        _lm_param_structure(vocab_size, max_len, d_model, n_heads,
                            n_layers, d_inner))

    scale = 1.0 / math.sqrt(d_head)
    # buffer donation makes the pool update in place (no copy of the
    # whole cache per token); CPU has no donation support and would
    # warn once per compile, so only donate where it lands
    donate = (1, 2) if jax.default_backend() != "cpu" else ()

    # -- pool storage: quantize-on-write / dequantize-on-gather ------------
    def _write(pool, l, wb, wi, row):
        """Write `row` [S, D] at (layer l, block wb[s], offset wi[s])."""
        if kv_dtype == "fp32":
            return pool.at[l, wb, wi].set(row)
        if kv_dtype == "bf16":
            return pool.at[l, wb, wi].set(row.astype(jnp.bfloat16))
        q, sc_ = pool
        # int8, one scale per (layer, block): re-quantize the whole
        # block under the running max.  Blocks fill strictly in
        # position order, so offsets < wi are the valid entries and
        # everything above is stale garbage that must NOT widen the
        # scale (a freshly-reused block holds a dead sequence's data).
        blk = q[l, wb].astype(jnp.float32)                  # [S, BS, D]
        s_old = sc_[l, wb]                                  # [S]
        deq = blk * s_old[:, None, None]
        offs = jnp.arange(bs)
        deq = jnp.where((offs[None, :] < wi[:, None])[..., None],
                        deq, 0.0)
        deq = jnp.where((offs[None, :] == wi[:, None])[..., None],
                        row[:, None, :], deq)
        m = jnp.max(jnp.abs(deq), axis=(1, 2))
        new_scale = jnp.maximum(m, 1e-8) / 127.0
        qn = jnp.clip(jnp.round(deq / new_scale[:, None, None]),
                      -127, 127).astype(jnp.int8)
        return (q.at[l, wb].set(qn), sc_.at[l, wb].set(new_scale))

    def _gather(pool, l, tables):
        """Dequantized [S, NB, BS, D] float32 view through the table."""
        if kv_dtype == "fp32":
            return pool[l][tables]
        if kv_dtype == "bf16":
            return pool[l][tables].astype(jnp.float32)
        q, sc_ = pool
        return (q[l][tables].astype(jnp.float32)
                * sc_[l][tables][:, :, None, None])

    def _sample(logits, seeds, positions, temps):
        """Greedy/sampled next token per row; stateless per-sequence
        sampling: the key depends only on (seed, position), never on
        the slot or tick number."""
        greedy = jnp.argmax(logits, axis=-1)
        subs = jax.vmap(
            lambda sd, p: jax.random.fold_in(jax.random.key(sd), p))(
                seeds, positions)
        safe_t = jnp.where(temps > 0, temps, 1.0)[:, None]
        sampled = jax.vmap(jax.random.categorical)(subs,
                                                   logits / safe_t)
        return jnp.where(temps > 0, sampled, greedy).astype(jnp.int32)

    @functools.partial(jax.jit, donate_argnums=donate)
    def step(g, pool_k, pool_v, tables, positions, tokens, seeds, temps,
             active):
        s_n = tokens.shape[0]
        lane = jnp.arange(s_n)

        def W(i):
            return g[weights[i]], g[biases[i]]

        def ln(x, i):
            sc_, b_ = g[lns[i][0]], g[lns[i][1]]
            mu = x.mean(-1, keepdims=True)
            var = ((x - mu) ** 2).mean(-1, keepdims=True)
            return (x - mu) / jnp.sqrt(var + 1e-5) * sc_ + b_

        x = g[tok_emb][tokens] + g[pos_tab][positions]       # [S, D]
        # this tick's K/V land at the cursor's (block, offset); inactive
        # slots are routed to block 0 offset 0 — the pool's reserved
        # null/scratch block, never owned by a sequence
        wb = jnp.where(active, tables[lane, positions // bs], 0)
        wi = jnp.where(active, positions % bs, 0)
        # mask over the table's logical span: position j participates
        # iff j <= cursor, which also hides unallocated tail entries
        pos_mask = jnp.arange(nb * bs)[None, :] <= positions[:, None]
        for l in range(n_layers):
            h = ln(x, 2 * l)
            wq, bq = W(6 * l + 0)
            wk, bk = W(6 * l + 1)
            wv, bv = W(6 * l + 2)
            wo, bo = W(6 * l + 3)
            q = h @ wq + bq
            kk = h @ wk + bk
            vv = h @ wv + bv
            pool_k = _write(pool_k, l, wb, wi, kk)
            pool_v = _write(pool_v, l, wb, wi, vv)
            if _attend is not None:
                # Pallas path: block-table reads + dequant + attention
                # in one kernel; bit-identical to the gather branch
                # (tests/test_serving_kernels.py)
                ctx_av = _attend(q[:, None, :], pool_k, pool_v, tables,
                                 positions, l)[:, 0]
            else:
                # gather-based attention over the block table:
                # [S, NB, BS, D] in table order IS logical order, so
                # after the reshape the math is the dense cache's math
                # on the same values
                kh = _gather(pool_k, l, tables).reshape(
                    s_n, nb * bs, n_heads, d_head)
                vh = _gather(pool_v, l, tables).reshape(
                    s_n, nb * bs, n_heads, d_head)
                qh = q.reshape(s_n, n_heads, d_head)
                sc = jnp.einsum("bhd,bshd->bhs", qh, kh) * scale
                sc = jnp.where(pos_mask[:, None, :], sc, -jnp.inf)
                w_att = jax.nn.softmax(sc, axis=-1)
                ctxh = jnp.einsum("bhs,bshd->bhd", w_att, vh)
                ctx_av = ctxh.reshape(s_n, d_model)
            x = x + (ctx_av @ wo + bo)
            h2 = ln(x, 2 * l + 1)
            w1, b1 = W(6 * l + 4)
            w2, b2 = W(6 * l + 5)
            x = x + (jax.nn.relu(h2 @ w1 + b1) @ w2 + b2)
        xf = ln(x, 2 * n_layers)
        wf, bf = W(6 * n_layers)
        logits = xf @ wf + bf                                # [S, V]
        nxt = _sample(logits, seeds, positions, temps)
        return nxt, pool_k, pool_v

    @functools.partial(jax.jit, donate_argnums=donate)
    def step_window(g, pool_k, pool_v, tables, positions, tokens, seeds,
                    temps, n_valid):
        # teacher-forced multi-position step: slot s processes window
        # positions positions[s]+j for j < n_valid[s] in one dispatch.
        # Rows past n_valid write to the null block; their predictions
        # are garbage the scheduler ignores.
        s_n, w_n = tokens.shape
        lane = jnp.arange(s_n)
        offs_w = jnp.arange(w_n)

        def W(i):
            return g[weights[i]], g[biases[i]]

        def ln(x, i):
            sc_, b_ = g[lns[i][0]], g[lns[i][1]]
            mu = x.mean(-1, keepdims=True)
            var = ((x - mu) ** 2).mean(-1, keepdims=True)
            return (x - mu) / jnp.sqrt(var + 1e-5) * sc_ + b_

        pos_w = positions[:, None] + offs_w[None, :]          # [S, W]
        valid = offs_w[None, :] < n_valid[:, None]            # [S, W]
        pos_c = jnp.clip(pos_w, 0, max_len - 1)
        x = g[tok_emb][tokens] + g[pos_tab][pos_c]            # [S, W, D]
        wb = jnp.where(valid,
                       tables[lane[:, None],
                              jnp.clip(pos_w // bs, 0, nb - 1)], 0)
        wi = jnp.where(valid, pos_w % bs, 0)
        # causal within the window AND over the committed span: window
        # row j attends to absolute positions <= positions[s]+j (row 0
        # reproduces `step`'s mask exactly)
        pos_mask = (jnp.arange(nb * bs)[None, None, :]
                    <= pos_w[:, :, None])                     # [S, W, L]
        for l in range(n_layers):
            h = ln(x, 2 * l)
            wq, bq = W(6 * l + 0)
            wk, bk = W(6 * l + 1)
            wv, bv = W(6 * l + 2)
            wo, bo = W(6 * l + 3)
            q = h @ wq + bq
            kk = h @ wk + bk
            vv = h @ wv + bv
            # the whole window's K/V is written before the gather, so
            # in-window attention sees the fresh values; int8 blocks
            # re-quantize per position, in order (the running-max
            # discipline needs offsets written low-to-high)
            for j in range(w_n):
                pool_k = _write(pool_k, l, wb[:, j], wi[:, j], kk[:, j])
                pool_v = _write(pool_v, l, wb[:, j], wi[:, j], vv[:, j])
            if _attend is not None:
                # speculative verify rides the SAME kernel as decode:
                # the window dim comes from q's shape at trace time
                ctx_av = _attend(q, pool_k, pool_v, tables, positions,
                                 l)
            else:
                kh = _gather(pool_k, l, tables).reshape(
                    s_n, nb * bs, n_heads, d_head)
                vh = _gather(pool_v, l, tables).reshape(
                    s_n, nb * bs, n_heads, d_head)
                qh = q.reshape(s_n, w_n, n_heads, d_head)
                sc = jnp.einsum("bqhd,bshd->bqhs", qh, kh) * scale
                sc = jnp.where(pos_mask[:, :, None, :], sc, -jnp.inf)
                w_att = jax.nn.softmax(sc, axis=-1)
                ctxh = jnp.einsum("bqhs,bshd->bqhd", w_att, vh)
                ctx_av = ctxh.reshape(s_n, w_n, d_model)
            x = x + (ctx_av @ wo + bo)
            h2 = ln(x, 2 * l + 1)
            w1, b1 = W(6 * l + 4)
            w2, b2 = W(6 * l + 5)
            x = x + (jax.nn.relu(h2 @ w1 + b1) @ w2 + b2)
        xf = ln(x, 2 * n_layers)
        wf, bf = W(6 * n_layers)
        logits = xf @ wf + bf                                 # [S, W, V]
        seeds_w = jnp.broadcast_to(seeds[:, None], (s_n, w_n))
        temps_w = jnp.broadcast_to(temps[:, None], (s_n, w_n))
        preds = _sample(logits.reshape(s_n * w_n, -1),
                        seeds_w.reshape(-1), pos_c.reshape(-1),
                        temps_w.reshape(-1)).reshape(s_n, w_n)
        return preds, pool_k, pool_v

    if kv_dtype == "fp32":
        elem_bytes = 4.0
    elif kv_dtype == "bf16":
        elem_bytes = 2.0
    else:
        # int8 payload + one f32 scale per (layer, block)
        elem_bytes = 1.0 + 4.0 / (bs * d_model)
    bytes_per_block = int(2 * n_layers * bs * d_model * elem_bytes)

    def init_pool(num_blocks, device=None):
        shape = (n_layers, int(num_blocks), bs, d_model)
        if kv_dtype == "int8":
            def z():
                return (jnp.zeros(shape, jnp.int8),
                        jnp.full((n_layers, int(num_blocks)), 1e-8,
                                 jnp.float32))
        elif kv_dtype == "bf16":
            def z():
                return jnp.zeros(shape, jnp.bfloat16)
        else:
            def z():
                return jnp.zeros(shape, jnp.float32)
        zk, zv = z(), z()
        if device is not None:
            zk = jax.device_put(zk, device)
            zv = jax.device_put(zv, device)
        return zk, zv

    import types

    decoder = types.SimpleNamespace(
        step=step, step_window=step_window, init_pool=init_pool,
        state_names=sorted(shapes), state_shapes=shapes, block_size=bs,
        max_blocks_per_seq=nb, max_len=max_len, n_layers=n_layers,
        d_model=d_model, vocab_size=vocab_size, kv_dtype=kv_dtype,
        bytes_per_block=bytes_per_block,
        kernel_selection=kernel_selection,
        kernels=dict(kernel_selection.chosen))
    return startup, decoder


def build_translate_generator(src_vocab, tgt_vocab, max_src_len,
                              max_tgt_len, d_model=256, n_heads=4,
                              n_layers=2, d_inner=None, bos_id=0,
                              eos_id=1):
    """Greedy translation decode for the encoder-decoder transformer,
    on-device (same single-jit fori_loop design as build_lm_generator:
    the full fixed-width decoder re-runs per step; the causal mask makes
    positions past the cursor inert).  The book seq2seq's host-side
    beam_search ops remain the LoD-era path; this is the static-shape
    transformer counterpart.

    Returns (startup_program, translate) where
      translate(states, src_ids [B, max_src_len], num_steps) ->
          tgt ids [B, max_tgt_len] starting with bos_id; positions after
          an emitted eos_id keep repeating eos_id.
    """
    import jax
    import jax.numpy as jnp

    from ..core.framework import Program, program_guard
    from ..core.executor import program_to_fn

    main, startup = Program(), Program()
    with program_guard(main, startup):
        src = layers.data(name="gen_src", shape=[max_src_len],
                          dtype="int64")
        tgt = layers.data(name="gen_tgt", shape=[max_tgt_len],
                          dtype="int64")
        probs = transformer_translate(
            src, tgt, src_vocab, tgt_vocab, d_model=d_model,
            n_heads=n_heads, n_layers=n_layers, d_inner=d_inner,
            max_len=max(max_src_len, max_tgt_len), is_test=True)
    fn = program_to_fn(main, ["gen_src", "gen_tgt"], [probs.name])

    import functools

    @functools.partial(jax.jit, static_argnames=("num_steps",))
    def _run(src_ids, tgt0, g, num_steps):
        def body(i, tgt):
            fetches, _ = fn({"gen_src": src_ids, "gen_tgt": tgt}, g,
                            jax.random.key(0))
            pr = fetches[probs.name]              # [B, T, V]
            step_p = jax.lax.dynamic_slice_in_dim(
                pr, i - 1, 1, axis=1)[:, 0]
            nxt = jnp.argmax(step_p, axis=-1).astype(jnp.int32)
            # once a row emitted eos, keep emitting eos
            prev = jax.lax.dynamic_slice_in_dim(
                tgt, i - 1, 1, axis=1)[:, 0]
            nxt = jnp.where(prev == eos_id, eos_id, nxt)
            return jax.lax.dynamic_update_slice(
                tgt, nxt[:, None], (0, i))

        return jax.lax.fori_loop(1, 1 + num_steps, body, tgt0)

    def translate(states, src_ids, num_steps):
        src_ids = jnp.asarray(src_ids, jnp.int32)
        b = src_ids.shape[0]
        assert num_steps < max_tgt_len
        tgt0 = jnp.full((b, max_tgt_len), eos_id, jnp.int32)
        tgt0 = tgt0.at[:, 0].set(bos_id)
        g = {n: jnp.asarray(v) for n, v in states.items()}
        return _run(src_ids, tgt0, g, int(num_steps))

    translate.state_names = list(fn.state_in_names)
    return startup, translate


def build_lm_beam_search(vocab_size, max_len, beam_size=4, d_model=256,
                         n_heads=4, n_layers=2, d_inner=None):
    """Static-shape beam search for the decoder-only LM, on-device.

    The LoD-era path (reference beam_search/beam_search_decode ops, kept
    for the book seq2seq) prunes hypotheses host-side with dynamic
    shapes; on TPU the beam is a fixed [B, K] lane structure folded into
    the batch: each step scores all K beams in one fixed-width forward
    (B*K rows), takes top-K over the K*V continuation scores, and
    gathers the winning prefixes — all inside one jit.

    Returns (startup_program, search) where
      search(states, prompt_ids [B, P], num_steps) ->
          (ids [B, K, max_len], scores [B, K]) sorted best-first;
    scores are sum log p.  (No EOS handling: all beams share one length,
    so GNMT-style length normalization would be a constant rescale —
    deliberately not offered as a knob.)
    """
    import functools

    import jax
    import jax.numpy as jnp

    from ..core.framework import Program, program_guard
    from ..core.executor import program_to_fn

    main, startup = Program(), Program()
    with program_guard(main, startup):
        ids_in = layers.data(name="gen_ids", shape=[max_len],
                             dtype="int64")
        probs = transformer_lm(ids_in, vocab_size, d_model=d_model,
                               n_heads=n_heads, n_layers=n_layers,
                               d_inner=d_inner, max_len=max_len,
                               is_test=True)
    fn = program_to_fn(main, ["gen_ids"], [probs.name])
    K = int(beam_size)

    @functools.partial(jax.jit, static_argnames=("p", "num_steps"))
    def _run(ids0, states, p, num_steps):
        b = ids0.shape[0]

        def body(i, carry):
            ids, scores = carry            # [B, K, L], [B, K]
            flat = ids.reshape(b * K, max_len)
            fetches, _ = fn({"gen_ids": flat}, states,
                            jax.random.key(0))
            pr = fetches[probs.name]       # [B*K, L, V]
            step_p = jax.lax.dynamic_slice_in_dim(
                pr, i - 1, 1, axis=1)[:, 0].reshape(b, K, vocab_size)
            logp = jnp.log(step_p + 1e-9)
            # at the first expansion only beam 0 is a real hypothesis
            first = (i == p)
            beam_mask = jnp.where(
                first,
                jnp.concatenate([jnp.zeros((1,)),
                                 jnp.full((K - 1,), -jnp.inf)])[None, :],
                jnp.zeros((1, K)))
            cand = scores[:, :, None] + logp + beam_mask[:, :, None]
            flat_cand = cand.reshape(b, K * vocab_size)
            top_scores, top_idx = jax.lax.top_k(flat_cand, K)   # [B, K]
            src_beam = top_idx // vocab_size
            tok = (top_idx % vocab_size).astype(jnp.int32)
            ids = jnp.take_along_axis(
                ids, src_beam[:, :, None], axis=1)              # regather
            ids = jax.lax.dynamic_update_slice(
                ids, tok[:, :, None], (0, 0, i))
            return ids, top_scores

        ids0 = jnp.broadcast_to(ids0[:, None, :],
                                (b, K, max_len)).copy()
        scores0 = jnp.zeros((b, K))
        ids, scores = jax.lax.fori_loop(p, p + num_steps, body,
                                        (ids0, scores0))
        return ids, scores

    def search(states, prompt_ids, num_steps):
        prompt_ids = jnp.asarray(prompt_ids, jnp.int32)
        b, p = prompt_ids.shape
        assert p + num_steps <= max_len
        ids0 = jnp.zeros((b, max_len), jnp.int32)
        ids0 = jax.lax.dynamic_update_slice(ids0, prompt_ids, (0, 0))
        g = {n: jnp.asarray(v) for n, v in states.items()}
        return _run(ids0, g, p, int(num_steps))

    search.state_names = list(fn.state_in_names)
    return startup, search
