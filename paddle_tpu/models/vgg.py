"""VGG model family.

Reference: /root/reference/benchmark/paddle/image/vgg.py and
/root/reference/python/paddle/v2/fluid/tests/book/
test_image_classification_train.py (vgg16_bn_drop),
benchmark/cluster/vgg16/vgg16_fluid.py.
"""
from __future__ import annotations

from .. import layers, nets

__all__ = ["vgg16_bn_drop", "vgg"]


def _conv_block(input, num_filter, groups, dropouts):
    return nets.img_conv_group(
        input=input,
        pool_size=2,
        pool_stride=2,
        conv_num_filter=[num_filter] * groups,
        conv_filter_size=3,
        conv_act="relu",
        conv_with_batchnorm=True,
        conv_batchnorm_drop_rate=dropouts,
        pool_type="max")


def vgg16_bn_drop(input, class_dim=10, is_test=False):
    """VGG-16 with batch norm + dropout (the book CIFAR model)."""
    conv1 = _conv_block(input, 64, 2, [0.3, 0.0])
    conv2 = _conv_block(conv1, 128, 2, [0.4, 0.0])
    conv3 = _conv_block(conv2, 256, 3, [0.4, 0.4, 0.0])
    conv4 = _conv_block(conv3, 512, 3, [0.4, 0.4, 0.0])
    conv5 = _conv_block(conv4, 512, 3, [0.4, 0.4, 0.0])
    drop = layers.dropout(x=conv5, dropout_prob=0.5, is_test=is_test)
    fc1 = layers.fc(input=drop, size=512, act=None)
    bn = layers.batch_norm(input=fc1, act="relu", is_test=is_test)
    drop2 = layers.dropout(x=bn, dropout_prob=0.5, is_test=is_test)
    fc2 = layers.fc(input=drop2, size=512, act=None)
    return layers.fc(input=fc2, size=class_dim, act="softmax")


def vgg(input, class_dim=1000, depth=16):
    """Plain VGG (no BN) as in benchmark/paddle/image/vgg.py."""
    cfg = {11: [1, 1, 2, 2, 2], 13: [2, 2, 2, 2, 2],
           16: [2, 2, 3, 3, 3], 19: [2, 2, 4, 4, 4]}[depth]
    chans = [64, 128, 256, 512, 512]
    tmp = input
    for c, g in zip(chans, cfg):
        for _ in range(g):
            tmp = layers.conv2d(input=tmp, num_filters=c, filter_size=3,
                                padding=1, act="relu")
        tmp = layers.pool2d(input=tmp, pool_size=2, pool_stride=2,
                            pool_type="max")
    fc1 = layers.fc(input=tmp, size=4096, act="relu")
    drop1 = layers.dropout(x=fc1, dropout_prob=0.5)
    fc2 = layers.fc(input=drop1, size=4096, act="relu")
    drop2 = layers.dropout(x=fc2, dropout_prob=0.5)
    return layers.fc(input=drop2, size=class_dim, act="softmax")
