"""Model zoo built on the layers DSL (reference book + benchmark models)."""
from .resnet import resnet_cifar10, resnet_imagenet  # noqa: F401
from .vgg import vgg, vgg16_bn_drop  # noqa: F401
