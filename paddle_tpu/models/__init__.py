"""Model zoo built on the layers DSL (reference book + benchmark models)."""
from .alexnet import alexnet  # noqa: F401
from .ctr import deepfm, wide_deep  # noqa: F401
from .googlenet import googlenet, smallnet_mnist_cifar  # noqa: F401
from .resnet import resnet_cifar10, resnet_imagenet  # noqa: F401
from .transformer import (  # noqa: F401
    transformer_decoder,
    transformer_encoder,
    transformer_lm,
    transformer_translate,
)
from .vgg import vgg, vgg16_bn_drop  # noqa: F401
