"""GoogLeNet / Inception-v1 (benchmark model).

Reference model def: /root/reference/benchmark/paddle/image/googlenet.py
(224x224, inception towers concat'd channel-wise, aux heads omitted in
timing mode like the reference's `small_vgg`-era bench) — rebuilt
fluid-style.
"""
from __future__ import annotations

from .. import layers

__all__ = ["googlenet"]


def _conv(input, num_filters, filter_size, stride=1, padding=0):
    return layers.conv2d(input=input, num_filters=num_filters,
                         filter_size=filter_size, stride=stride,
                         padding=padding, act="relu")


def inception(input, f1, f3r, f3, f5r, f5, proj):
    """One inception tower (reference googlenet.py:108-193): 1x1 | 1x1→3x3
    | 1x1→5x5 | 3x3maxpool→1x1, concat on channels."""
    c1 = _conv(input, f1, 1)
    c3 = _conv(_conv(input, f3r, 1), f3, 3, padding=1)
    c5 = _conv(_conv(input, f5r, 1), f5, 5, padding=2)
    pj = _conv(layers.pool2d(input=input, pool_size=3, pool_stride=1,
                             pool_padding=1, pool_type="max"), proj, 1)
    return layers.concat([c1, c3, c5, pj], axis=1)


def googlenet(input, class_dim=1000, is_test=False):
    """[N, 3, 224, 224] NCHW input -> softmax over class_dim."""
    conv1 = _conv(input, 64, 7, stride=2, padding=3)
    pool1 = layers.pool2d(input=conv1, pool_size=3, pool_stride=2,
                          pool_type="max")
    conv2 = _conv(_conv(pool1, 64, 1), 192, 3, padding=1)
    pool2 = layers.pool2d(input=conv2, pool_size=3, pool_stride=2,
                          pool_type="max")

    i3a = inception(pool2, 64, 96, 128, 16, 32, 32)
    i3b = inception(i3a, 128, 128, 192, 32, 96, 64)
    pool3 = layers.pool2d(input=i3b, pool_size=3, pool_stride=2,
                          pool_type="max")

    i4a = inception(pool3, 192, 96, 208, 16, 48, 64)
    i4b = inception(i4a, 160, 112, 224, 24, 64, 64)
    i4c = inception(i4b, 128, 128, 256, 24, 64, 64)
    i4d = inception(i4c, 112, 144, 288, 32, 64, 64)
    i4e = inception(i4d, 256, 160, 320, 32, 128, 128)
    pool4 = layers.pool2d(input=i4e, pool_size=3, pool_stride=2,
                          pool_type="max")

    i5a = inception(pool4, 256, 160, 320, 32, 128, 128)
    i5b = inception(i5a, 384, 192, 384, 48, 128, 128)
    pool5 = layers.pool2d(input=i5b, pool_type="avg", global_pooling=True)
    drop = layers.dropout(pool5, dropout_prob=0.4, is_test=is_test)
    return layers.fc(input=drop, size=class_dim, act="softmax")


def smallnet_mnist_cifar(input, class_dim=10):
    """SmallNet (reference benchmark/paddle/image/smallnet_mnist_cifar.py):
    3 conv/pool stages + 2 fc for 32x32 inputs."""
    c1 = layers.conv2d(input=input, num_filters=32, filter_size=5,
                       padding=2, act="relu")
    p1 = layers.pool2d(input=c1, pool_size=3, pool_stride=2,
                       pool_padding=1, pool_type="max")
    c2 = layers.conv2d(input=p1, num_filters=32, filter_size=5,
                       padding=2, act="relu")
    p2 = layers.pool2d(input=c2, pool_size=3, pool_stride=2,
                       pool_padding=1, pool_type="avg")
    c3 = layers.conv2d(input=p2, num_filters=64, filter_size=3,
                       padding=1, act="relu")
    p3 = layers.pool2d(input=c3, pool_size=3, pool_stride=2,
                       pool_padding=1, pool_type="avg")
    f1 = layers.fc(input=p3, size=64, act="relu")
    return layers.fc(input=f1, size=class_dim, act="softmax")
