"""CTR / sparse-recommendation model family: wide&deep and DeepFM.

The reference's sparse story is the `is_sparse` lookup_table whose gradient
is a SelectedRows of touched rows
(/root/reference/paddle/fluid/operators/lookup_table_op.cc:114-131) plus
remote sparse embedding on parameter servers
(/root/reference/doc/design/cluster_train/large_model_dist_train.md).  The
rebuild keeps the same API surface (embedding(is_sparse=True) -> touched-row
grads) and these models exercise it the way the reference's CTR users did:
many categorical slots, one embedding table per slot, optional row-sharded
tables over a mesh axis (parallel/collective.py sharded_embedding_lookup).

Every categorical slot takes [batch, 1] int64 ids; `dense_input` is
[batch, dense_dim] float.
"""
from __future__ import annotations

from .. import layers

__all__ = ["wide_deep", "deepfm"]


def _slot_embeddings(sparse_inputs, vocab_sizes, dim, is_sparse):
    if len(sparse_inputs) != len(vocab_sizes):
        raise ValueError(
            f"{len(sparse_inputs)} sparse slots but "
            f"{len(vocab_sizes)} vocab sizes")
    return [
        layers.embedding(ids, size=[int(v), dim], is_sparse=is_sparse)
        for ids, v in zip(sparse_inputs, vocab_sizes)
    ]


def _wide_part(dense_input, sparse_inputs, vocab_sizes, is_sparse):
    """Linear model: per-category scalar weights + linear dense term."""
    terms = _slot_embeddings(sparse_inputs, vocab_sizes, 1, is_sparse)
    if dense_input is not None:
        terms.append(layers.fc(input=dense_input, size=1, bias_attr=False))
    return layers.sums([layers.reshape(t, shape=[-1, 1]) for t in terms])


def _deep_part(dense_input, embs, hidden_sizes):
    feats = [layers.reshape(e, shape=[0, -1]) for e in embs]
    if dense_input is not None:
        feats.append(dense_input)
    x = layers.concat(feats, axis=1) if len(feats) > 1 else feats[0]
    for h in hidden_sizes:
        x = layers.fc(input=x, size=h, act="relu")
    return layers.fc(input=x, size=1)


def wide_deep(sparse_inputs, vocab_sizes, dense_input=None, embed_dim=8,
              hidden_sizes=(64, 32), is_sparse=True):
    """Wide&Deep CTR model -> (prob, logit), both [batch, 1]."""
    wide = _wide_part(dense_input, sparse_inputs, vocab_sizes, is_sparse)
    embs = _slot_embeddings(sparse_inputs, vocab_sizes, embed_dim,
                            is_sparse)
    deep = _deep_part(dense_input, embs, hidden_sizes)
    logit = layers.elementwise_add(wide, deep)
    return layers.sigmoid(logit), logit


def deepfm(sparse_inputs, vocab_sizes, dense_input=None, embed_dim=8,
           hidden_sizes=(64, 32), is_sparse=True):
    """DeepFM -> (prob, logit): wide (1st order) + FM (2nd order pairwise
    interactions, O(fields*dim)) + deep tower, sharing one set of slot
    embeddings between FM and deep."""
    first = _wide_part(dense_input, sparse_inputs, vocab_sizes, is_sparse)
    embs = _slot_embeddings(sparse_inputs, vocab_sizes, embed_dim,
                            is_sparse)
    flat = [layers.reshape(e, shape=[-1, embed_dim]) for e in embs]
    # FM trick: 0.5 * sum_k[(sum_i e_ik)^2 - sum_i e_ik^2]
    sum_e = layers.sums(flat)
    sum_sq = layers.square(sum_e)
    sq_sum = layers.sums([layers.square(e) for e in flat])
    fm = layers.scale(
        layers.reduce_sum(layers.elementwise_sub(sum_sq, sq_sum), dim=1,
                          keep_dim=True), scale=0.5)
    deep = _deep_part(dense_input, embs, hidden_sizes)
    logit = layers.sums([first, fm, deep])
    return layers.sigmoid(logit), logit
