"""ResNet model family built through the layers DSL.

Reference model defs: /root/reference/benchmark/paddle/image/resnet.py
(224x224 ImageNet, layer_num 50/101/152) and
/root/reference/python/paddle/v2/fluid/tests/book/test_image_classification_train.py
(resnet_cifar10).  Rebuilt fluid-style: conv2d + batch_norm + elementwise_add
residual blocks; XLA fuses BN+ReLU into the conv epilogues on TPU.
"""
from __future__ import annotations

from .. import layers
from ..core.flags import get_flag

__all__ = ["resnet_imagenet", "resnet_cifar10"]


def _default_remat(remat):
    """remat=None defers to the `remat` flag (PADDLE_TPU_REMAT): the
    build-time knob that wraps every residual block in layers.recompute
    (jax.checkpoint) — activations re-run in backward instead of living
    in HBM (Chen et al., sublinear memory cost; docs/performance.md
    'Memory')."""
    return bool(get_flag("remat")) if remat is None else bool(remat)


def conv_bn_layer(input, ch_out, filter_size, stride, padding, act="relu",
                  bias_attr=False, is_test=False, data_format="NCHW"):
    conv = layers.conv2d(input=input, num_filters=ch_out,
                         filter_size=filter_size, stride=stride,
                         padding=padding, act=None, bias_attr=bias_attr,
                         data_format=data_format)
    return layers.batch_norm(input=conv, act=act, is_test=is_test,
                             data_layout=data_format)


def _shortcut(input, ch_in, ch_out, stride, is_test=False,
              data_format="NCHW"):
    if stride != 1 or ch_in != ch_out:
        return conv_bn_layer(input, ch_out, 1, stride, 0, act=None,
                             is_test=is_test, data_format=data_format)
    return input


def _add_relu(a, b):
    s = layers.elementwise_add(a, b)
    return layers.relu(s)


def basicblock(input, ch_in, ch_out, stride, is_test=False,
               data_format="NCHW"):
    short = _shortcut(input, ch_in, ch_out, stride, is_test, data_format)
    conv1 = conv_bn_layer(input, ch_out, 3, stride, 1, is_test=is_test,
                          data_format=data_format)
    conv2 = conv_bn_layer(conv1, ch_out, 3, 1, 1, act=None, is_test=is_test,
                          data_format=data_format)
    return _add_relu(short, conv2)


def bottleneck(input, ch_in, ch_out, stride, is_test=False,
               data_format="NCHW"):
    short = _shortcut(input, ch_in, ch_out * 4, stride, is_test, data_format)
    conv1 = conv_bn_layer(input, ch_out, 1, stride, 0, is_test=is_test,
                          data_format=data_format)
    conv2 = conv_bn_layer(conv1, ch_out, 3, 1, 1, is_test=is_test,
                          data_format=data_format)
    conv3 = conv_bn_layer(conv2, ch_out * 4, 1, 1, 0, act=None,
                          is_test=is_test, data_format=data_format)
    return _add_relu(short, conv3)


def _layer_warp(block_func, input, ch_in, ch_out, count, stride,
                is_test=False, data_format="NCHW", remat=False):
    def blk(x, ci, st):
        if remat:
            # rematerialized residual block: its internal conv/BN
            # activations re-run in backward instead of living in HBM —
            # the bytes-for-FLOPs trade for a memory-bound conv net
            # (BN running-stat writes survive; layers.recompute carries
            # persistable writes out of the segment)
            return layers.recompute(
                lambda: block_func(x, ci, ch_out, st, is_test,
                                   data_format))
        return block_func(x, ci, ch_out, st, is_test, data_format)

    res = blk(input, ch_in, stride)
    for _ in range(1, count):
        ch_in_cur = ch_out * (4 if block_func is bottleneck else 1)
        res = blk(res, ch_in_cur, 1)
    return res


def resnet_imagenet(input, class_dim=1000, depth=50, is_test=False,
                    data_format="NCHW", remat=None):
    """ResNet-50/101/152 (bottleneck) for 224x224 input; data_format
    "NHWC" runs channels-last — the TPU-native conv layout.  `remat=True`
    wraps every residual block in layers.recompute (jax.checkpoint):
    block-internal activations are recomputed in backward — the HBM
    lever for this memory-bound model (benchmark/README.md bytes
    analysis; BENCH_REMAT=1 measures it)."""
    remat = _default_remat(remat)
    cfg = {
        50: ([3, 4, 6, 3], bottleneck),
        101: ([3, 4, 23, 3], bottleneck),
        152: ([3, 8, 36, 3], bottleneck),
        18: ([2, 2, 2, 2], basicblock),
        34: ([3, 4, 6, 3], basicblock),
    }
    stages, block = cfg[depth]
    conv1 = conv_bn_layer(input, 64, 7, 2, 3, is_test=is_test,
                          data_format=data_format)
    pool1 = layers.pool2d(input=conv1, pool_size=3, pool_stride=2,
                          pool_padding=1, pool_type="max",
                          data_format=data_format)
    expansion = 4 if block is bottleneck else 1
    res = pool1
    ch_in = 64
    for i, (count, ch_out) in enumerate(zip(stages, [64, 128, 256, 512])):
        stride = 1 if i == 0 else 2
        res = _layer_warp(block, res, ch_in, ch_out, count, stride, is_test,
                          data_format, remat=remat)
        ch_in = ch_out * expansion
    pool2 = layers.pool2d(input=res, pool_type="avg", global_pooling=True,
                          data_format=data_format)
    return layers.fc(input=pool2, size=class_dim, act="softmax")


def resnet_cifar10(input, class_dim=10, depth=32, is_test=False,
                   remat=None):
    """CIFAR ResNet (basicblock), depth = 6n+2 (reference book model).
    `remat` as in resnet_imagenet (None = the `remat` flag)."""
    assert (depth - 2) % 6 == 0
    remat = _default_remat(remat)
    n = (depth - 2) // 6
    conv1 = conv_bn_layer(input, 16, 3, 1, 1, is_test=is_test)
    res1 = _layer_warp(basicblock, conv1, 16, 16, n, 1, is_test,
                       remat=remat)
    res2 = _layer_warp(basicblock, res1, 16, 32, n, 2, is_test,
                       remat=remat)
    res3 = _layer_warp(basicblock, res2, 32, 64, n, 2, is_test,
                       remat=remat)
    pool = layers.pool2d(input=res3, pool_type="avg", global_pooling=True)
    return layers.fc(input=pool, size=class_dim, act="softmax")
