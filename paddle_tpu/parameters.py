"""v2-style ``Parameters`` facade: name-addressed access to a model's
parameters plus single-file tar round-tripping.

Reference: /root/reference/python/paddle/v2/parameters.py (keys :116,
get/set :200-239, to_tar :242, from_tar :274, init_from_tar :300).  The
reference stores each parameter as a ParameterConfig proto + raw bytes in a
tar; here each member is a ``.npy`` (dtype+shape self-describing) plus a
``meta.json`` manifest, and values live in a Scope instead of the gserver
GradientMachine.
"""
from __future__ import annotations

import io as _io
import json
import tarfile
from typing import Dict, List, Optional

import numpy as np

from .core.framework import Parameter, Program, default_main_program
from .core.executor import global_scope
from .core.scope import Scope

__all__ = ["Parameters"]


class Parameters:
    """Dict-like view over the parameter variables of a ``Program``.

    Values are read/written through a ``Scope`` (the runtime store), so a
    ``Parameters`` handle stays live: mutations made by training are visible
    through ``get`` and ``set`` writes feed subsequent runs.
    """

    def __init__(self, program: Optional[Program] = None,
                 scope: Optional[Scope] = None):
        self._program = program or default_main_program()
        self._scope = scope or global_scope()

    # -- introspection ----------------------------------------------------
    def _param_vars(self) -> Dict[str, object]:
        out = {}
        for block in self._program.blocks:
            for var in block.vars.values():
                if isinstance(var, Parameter):
                    out.setdefault(var.name, var)
        return out

    def names(self) -> List[str]:
        return sorted(self._param_vars())

    keys = names

    def has_key(self, name: str) -> bool:
        return name in self._param_vars()

    def __contains__(self, name: str) -> bool:
        return self.has_key(name)

    def __iter__(self):
        return iter(self.names())

    def __len__(self):
        return len(self._param_vars())

    def get_shape(self, name: str):
        var = self._param_vars()[name]
        return tuple(int(d) for d in var.shape)

    # -- value access -----------------------------------------------------
    def get(self, name: str) -> np.ndarray:
        var = self._scope.find_var(name)
        if var is None:
            raise KeyError(f"parameter '{name}' has no value in scope "
                           "(run the startup program first)")
        return np.asarray(var)

    __getitem__ = get

    def set(self, name: str, value) -> None:
        if name not in self._param_vars():
            raise KeyError(f"'{name}' is not a parameter of the program")
        value = np.asarray(value)
        shape = self.get_shape(name)
        if tuple(value.shape) != shape:
            raise ValueError(
                f"shape mismatch for '{name}': got {value.shape}, "
                f"parameter is {shape}")
        self._scope.set_var(name, value)

    __setitem__ = set

    # -- serialization ----------------------------------------------------
    def to_tar(self, f) -> None:
        """Write every parameter into one tar stream (v2 to_tar parity)."""
        with tarfile.open(fileobj=f, mode="w") as tar:
            meta = {}
            for name in self.names():
                arr = self.get(name)
                meta[name] = {"shape": list(arr.shape),
                              "dtype": str(arr.dtype)}
                buf = _io.BytesIO()
                np.save(buf, arr)
                data = buf.getvalue()
                ti = tarfile.TarInfo(name=name + ".npy")
                ti.size = len(data)
                tar.addfile(ti, _io.BytesIO(data))
            mbytes = json.dumps(meta, indent=1, sort_keys=True).encode()
            ti = tarfile.TarInfo(name="meta.json")
            ti.size = len(mbytes)
            tar.addfile(ti, _io.BytesIO(mbytes))

    @staticmethod
    def _iter_tar_arrays(f):
        """Yield (name, ndarray) for every .npy member of a params tar."""
        with tarfile.open(fileobj=f, mode="r") as tar:
            for member in tar.getmembers():
                if not member.name.endswith(".npy"):
                    continue
                name = member.name[:-len(".npy")]
                arr = np.load(_io.BytesIO(tar.extractfile(member).read()),
                              allow_pickle=False)
                yield name, arr

    def init_from_tar(self, f) -> None:
        """Load values for parameters present in BOTH tar and program
        (v2 init_from_tar semantics: extra tar entries are ignored)."""
        own = self._param_vars()
        for name, arr in self._iter_tar_arrays(f):
            if name in own:
                self.set(name, arr)

    @staticmethod
    def from_tar(f) -> "Parameters":
        """Construct a NEW ``Parameters`` solely from a tar stream
        (reference v2 parameters.py:274 ``@staticmethod from_tar``): a
        detached Program holding one Parameter var per tar entry and a
        private Scope with the loaded values.  Use ``init_from_tar`` to
        load values into an existing program's parameters in place."""
        prog = Program()
        scope = Scope()
        blk = prog.global_block()
        for name, arr in Parameters._iter_tar_arrays(f):
            blk.create_parameter(name, list(arr.shape), str(arr.dtype))
            scope.set_var(name, arr)
        return Parameters(prog, scope)
