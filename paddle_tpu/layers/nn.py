"""Rich NN layers.

Reference: /root/reference/python/paddle/v2/fluid/layers/nn.py (fc :74,
embedding :195, conv2d :1137, batch_norm :1482, layer_norm :1570,
matmul :2388, softmax_with_cross_entropy :3098, …).
"""
from __future__ import annotations

from ..core.framework import Variable
from ..initializer import ConstantInitializer
from ..layer_helper import LayerHelper

__all__ = [
    "fc",
    "embedding",
    "dropout",
    "flash_attention",
    "moe_ffn",
    "cross_entropy",
    "square_error_cost",
    "cos_sim",
    "linear_chain_crf",
    "crf_decoding",
    "accuracy",
    "auc",
    "edit_distance",
    "warpctc",
    "ctc_align",
    "nce",
    "hsigmoid",
    "chunk_eval",
    "conv2d",
    "conv2d_transpose",
    "pool2d",
    "batch_norm",
    "layer_norm",
    "lrn",
    "mean",
    "mul",
    "matmul",
    "reduce_sum",
    "reduce_mean",
    "reduce_max",
    "reduce_min",
    "topk",
    "softmax_with_cross_entropy",
    "sigmoid_cross_entropy_with_logits",
    "split",
    "l2_normalize",
    "one_hot",
    "autoincreased_step_counter",
    "smooth_l1",
    "dynamic_lstm",
    "dynamic_lstmp",
    "dynamic_gru",
    "gru_unit",
    "lstm_unit",
    "row_conv",
    "multiplex",
    "ctc_greedy_decoder",
    "sequence_conv",
    "sequence_pool",
    "sequence_first_step",
    "sequence_last_step",
    "sequence_softmax",
    "sequence_expand",
    "sequence_reshape",
    "lod_reset",
    "im2sequence",
]


def fc(input, size, num_flatten_dims=1, param_attr=None, bias_attr=None,
       act=None, name=None, main_program=None, startup_program=None,
       is_test=False, use_mkldnn=False):
    """Fully-connected: mul per input + sum + bias + act
    (reference layers/nn.py:74)."""
    helper = LayerHelper("fc", input=input, param_attr=param_attr,
                         bias_attr=bias_attr, act=act, name=name,
                         main_program=main_program,
                         startup_program=startup_program)
    dtype = helper.input_dtype()
    mul_results = []
    for input_var in helper.multiple_input():
        input_shape = input_var.shape
        param_shape = [
            abs(int(__import__("numpy").prod(
                input_shape[num_flatten_dims:])))
        ] + [size]
        w = helper.create_parameter(param_attr, param_shape, dtype,
                                    suffix="w")
        tmp = helper.create_tmp_variable(dtype)
        helper.append_op(
            "mul", {"X": [input_var.name], "Y": [w.name]},
            {"Out": [tmp.name]},
            {"x_num_col_dims": num_flatten_dims, "y_num_col_dims": 1})
        mul_results.append(tmp)
    if len(mul_results) == 1:
        pre_bias = mul_results[0]
    else:
        pre_bias = helper.create_tmp_variable(dtype)
        helper.append_op("sum", {"X": [v.name for v in mul_results]},
                         {"Out": [pre_bias.name]})
    # bias covers only the projected dims (reference layers/nn.py:74 passes
    # dim_start=num_flatten_dims) — a [size] bias, not [*batch_dims, size]
    pre_act = helper.append_bias_op(pre_bias, dim_start=num_flatten_dims)
    return helper.append_activation(pre_act)


def embedding(input, size, is_sparse=False, padding_idx=None,
              param_attr=None, dtype="float32"):
    """Lookup-table layer (reference layers/nn.py:195).  `is_sparse=True`
    makes the gradient a SelectedRows (lookup_table_op.cc:114-131
    VarTypeInference analogue)."""
    helper = LayerHelper("embedding", param_attr=param_attr)
    w = helper.create_parameter(param_attr, size, dtype, suffix="w")
    tmp = helper.create_tmp_variable(dtype)
    tmp.lod_level = input.lod_level
    tmp.shape = (-1, int(size[1]))
    helper.append_op(
        "lookup_table", {"Ids": [input.name], "W": [w.name]},
        {"Out": [tmp.name]},
        {"is_sparse": bool(is_sparse),
         "padding_idx": -1 if padding_idx is None else int(padding_idx)})
    return tmp


def dropout(x, dropout_prob, is_test=False, seed=0, name=None):
    helper = LayerHelper("dropout", name=name)
    out = helper.create_tmp_variable(x.dtype)
    mask = helper.create_tmp_variable(x.dtype, stop_gradient=True)
    helper.append_op("dropout", {"X": [x.name]},
                     {"Out": [out.name], "Mask": [mask.name]},
                     {"dropout_prob": float(dropout_prob),
                      "is_test": is_test, "seed": seed,
                      "fix_seed": seed != 0})
    return out


def flash_attention(q, k, v, causal=False, scale=None, min_seq_k=None,
                    name=None):
    """Fused attention over [batch, seq, heads, head_dim] tensors, lowered
    to the Pallas flash-attention kernel (kernels/flash_attention.py) for
    long sequences and XLA's fused composition below the measured
    crossover (min_seq_k=None -> kernel policy default ~2k; 0 forces the
    kernel).  No reference analogue — the reference composes attention
    from matmuls (nets.py:162-219); this is the TPU-native hot path."""
    helper = LayerHelper("flash_attention", name=name)
    out = helper.create_tmp_variable(q.dtype)
    out.shape = q.shape
    helper.append_op("flash_attention",
                     {"Q": [q.name], "K": [k.name], "V": [v.name]},
                     {"Out": [out.name]},
                     {"causal": bool(causal),
                      "scale": 1.0 if scale is None else float(scale),
                      "default_scale": scale is None,
                      "min_seq_k": -1 if min_seq_k is None
                      else int(min_seq_k)})
    return out


def moe_ffn(input, num_experts, d_inner=None, top_k=1,
            capacity_factor=1.25, param_attr=None, name=None):
    """Mixture-of-Experts FFN layer (no reference analogue — the EP
    subsystem the TPU rebuild adds; parallel/moe.py holds the math and
    the shard_map/all_to_all execution forms).

    input: [..., D] activations; builds a [D, E] router plus per-expert
    [E, D, H]/[E, H, D] FFN weights and returns (out [..., D],
    aux_loss [1]).  Add `weight * aux_loss` to the training loss to
    train the router toward load balance (Switch eq. 4).  Under
    ParallelExecutor pass `param_shardings` mapping the w_in/w_out
    parameter names to PartitionSpec("ep") to shard the expert dim.
    """
    helper = LayerHelper("moe_ffn", input=input, param_attr=param_attr,
                         name=name)
    dtype = helper.input_dtype()
    d = int(input.shape[-1])
    d_inner = int(d_inner or 4 * d)
    num_experts = int(num_experts)

    def attr_for(suffix):
        # three differently-shaped params from ONE param_attr: an
        # explicit name must fan out per suffix or create_parameter
        # would silently overwrite the same variable three times
        a = dict(param_attr or {})
        if a.get("name"):
            a["name"] = f"{a['name']}.{suffix}"
        return a

    gate_w = helper.create_parameter(attr_for("gate_w"),
                                     [d, num_experts], dtype,
                                     suffix="gate_w")
    w_in = helper.create_parameter(attr_for("w_in"),
                                   [num_experts, d, d_inner],
                                   dtype, suffix="w_in")
    w_out = helper.create_parameter(attr_for("w_out"),
                                    [num_experts, d_inner, d],
                                    dtype, suffix="w_out")
    out = helper.create_tmp_variable(dtype)
    out.shape = input.shape
    aux = helper.create_tmp_variable(dtype)
    aux.shape = [1]
    helper.append_op("moe_ffn",
                     {"X": [input.name], "GateW": [gate_w.name],
                      "WIn": [w_in.name], "WOut": [w_out.name]},
                     {"Out": [out.name], "AuxLoss": [aux.name]},
                     {"top_k": int(top_k),
                      "capacity_factor": float(capacity_factor)})
    return out, aux


def cross_entropy(input, label, soft_label=False):
    helper = LayerHelper("cross_entropy")
    out = helper.create_tmp_variable(input.dtype)
    helper.append_op("cross_entropy",
                     {"X": [input.name], "Label": [label.name]},
                     {"Y": [out.name]}, {"soft_label": soft_label})
    return out


def square_error_cost(input, label):
    """(input - label)^2, reference layers/nn.py square_error_cost."""
    helper = LayerHelper("square_error_cost")
    minus_out = helper.create_tmp_variable(input.dtype)
    helper.append_op("elementwise_sub",
                     {"X": [input.name], "Y": [label.name]},
                     {"Out": [minus_out.name]}, {"axis": -1})
    square_out = helper.create_tmp_variable(input.dtype)
    helper.append_op("square", {"X": [minus_out.name]},
                     {"Out": [square_out.name]})
    return square_out


def linear_chain_crf(input, label, param_attr=None):
    """CRF negative log-likelihood cost over a LoD emission sequence
    (reference layers/nn.py linear_chain_crf, linear_chain_crf_op.cc).
    The transition parameter has shape [D+2, D] (start/end rows first)."""
    helper = LayerHelper("linear_chain_crf", param_attr=param_attr)
    size = input.shape[-1]
    transition = helper.create_parameter(param_attr, [size + 2, size],
                                         input.dtype, suffix="transition")
    alpha = helper.create_tmp_variable(input.dtype, stop_gradient=True)
    em_exps = helper.create_tmp_variable(input.dtype, stop_gradient=True)
    tr_exps = helper.create_tmp_variable(input.dtype, stop_gradient=True)
    log_likelihood = helper.create_tmp_variable(input.dtype)
    helper.append_op(
        "linear_chain_crf",
        {"Emission": [input.name], "Transition": [transition.name],
         "Label": [label.name]},
        {"Alpha": [alpha.name], "EmissionExps": [em_exps.name],
         "TransitionExps": [tr_exps.name],
         "LogLikelihood": [log_likelihood.name]})
    return log_likelihood


def crf_decoding(input, param_attr, label=None):
    """Viterbi decode using the transition parameter learned by
    linear_chain_crf (shared via param_attr name)."""
    helper = LayerHelper("crf_decoding", param_attr=param_attr)
    name = (param_attr or {}).get("name")
    block = helper.main_program.global_block()
    if name and name in block.vars:
        transition = block.vars[name]
    else:
        size = input.shape[-1]
        transition = helper.create_parameter(param_attr, [size + 2, size],
                                             input.dtype,
                                             suffix="transition")
    path = helper.create_tmp_variable("int64", stop_gradient=True)
    inputs = {"Emission": [input.name], "Transition": [transition.name]}
    if label is not None:
        inputs["Label"] = [label.name]
    helper.append_op("crf_decoding", inputs,
                     {"ViterbiPath": [path.name]})
    return path


def cos_sim(X, Y):
    """Row-wise cosine similarity (reference layers/nn.py cos_sim,
    operators/cos_sim_op.cc); Y may have a single row, broadcast to X."""
    helper = LayerHelper("cos_sim")
    out = helper.create_tmp_variable(X.dtype)
    xnorm = helper.create_tmp_variable(X.dtype, stop_gradient=True)
    ynorm = helper.create_tmp_variable(X.dtype, stop_gradient=True)
    helper.append_op("cos_sim", {"X": [X.name], "Y": [Y.name]},
                     {"Out": [out.name], "XNorm": [xnorm.name],
                      "YNorm": [ynorm.name]})
    return out


def accuracy(input, label, k=1, correct=None, total=None):
    """top-k accuracy metric built from top_k + accuracy ops
    (reference layers/nn.py accuracy)."""
    helper = LayerHelper("accuracy")
    topk_out = helper.create_tmp_variable(input.dtype, stop_gradient=True)
    topk_indices = helper.create_tmp_variable("int64", stop_gradient=True)
    helper.append_op("top_k", {"X": [input.name]},
                     {"Out": [topk_out.name],
                      "Indices": [topk_indices.name]}, {"k": k})
    acc_out = helper.create_tmp_variable("float32", stop_gradient=True)
    correct = correct or helper.create_tmp_variable("int32",
                                                    stop_gradient=True)
    total = total or helper.create_tmp_variable("int32", stop_gradient=True)
    helper.append_op(
        "accuracy",
        {"Out": [topk_out.name], "Indices": [topk_indices.name],
         "Label": [label.name]},
        {"Accuracy": [acc_out.name], "Correct": [correct.name],
         "Total": [total.name]})
    return acc_out


def warpctc(input, label, blank=0, norm_by_times=False):
    """CTC loss over LoD sequences (reference layers/nn.py:2659 warpctc;
    computed natively — see ops/ctc.py)."""
    helper = LayerHelper("warpctc")
    loss = helper.create_tmp_variable("float32")
    grad = helper.create_tmp_variable("float32", stop_gradient=True)
    helper.append_op(
        "warpctc",
        {"Logits": [input.name], "Label": [label.name]},
        {"Loss": [loss.name], "WarpCTCGrad": [grad.name]},
        {"blank": int(blank), "norm_by_times": bool(norm_by_times)})
    return loss


def ctc_align(input, blank=0, merge_repeated=True):
    """Greedy CTC decode (reference ctc_align_op.cc)."""
    helper = LayerHelper("ctc_align")
    out = helper.create_tmp_variable("int64", stop_gradient=True)
    out.lod_level = 1
    helper.append_op("ctc_align", {"Input": [input.name]},
                     {"Output": [out.name]},
                     {"blank": int(blank),
                      "merge_repeated": bool(merge_repeated)})
    return out


def nce(input, label, num_total_classes, sample_weight=None,
        param_attr=None, bias_attr=None, num_neg_samples=None):
    """Noise-contrastive estimation loss (reference layers/nn.py:2769)."""
    helper = LayerHelper("nce", param_attr=param_attr, bias_attr=bias_attr)
    dim = int(input.shape[1])
    w = helper.create_parameter(helper.param_attr,
                                [num_total_classes, dim], input.dtype,
                                suffix="w")
    # bias_attr=False disables the bias (layer_helper convention); the nce
    # op lowering handles Bias=None
    b = None
    if bias_attr is not False:
        ba = {} if bias_attr in (None, True) else dict(bias_attr)
        b = helper.create_parameter(ba, [num_total_classes], input.dtype,
                                    is_bias=True, suffix="b")
    if num_neg_samples is None:
        num_neg_samples = 10
    cost = helper.create_tmp_variable(input.dtype)
    sample_logits = helper.create_tmp_variable(input.dtype,
                                               stop_gradient=True)
    sample_labels = helper.create_tmp_variable("int64", stop_gradient=True)
    inputs = {"Input": [input.name], "Label": [label.name],
              "Weight": [w.name]}
    if b is not None:
        inputs["Bias"] = [b.name]
    if sample_weight is not None:
        inputs["SampleWeight"] = [sample_weight.name]
    helper.append_op(
        "nce", inputs,
        {"Cost": [cost.name], "SampleLogits": [sample_logits.name],
         "SampleLabels": [sample_labels.name]},
        {"num_total_classes": int(num_total_classes),
         "num_neg_samples": int(num_neg_samples)})
    cost.shape = (-1, 1)
    return cost


def hsigmoid(input, label, num_classes, param_attr=None, bias_attr=None):
    """Hierarchical sigmoid cost, [batch, 1] (reference
    gserver/layers/HierarchicalSigmoidLayer.cpp — the one sampled-softmax
    variant the reference keeps legacy-only)."""
    helper = LayerHelper("hsigmoid", param_attr=param_attr,
                         bias_attr=bias_attr)
    dim = int(input.shape[-1])
    w = helper.create_parameter(helper.param_attr, [num_classes - 1, dim],
                                input.dtype, suffix="w")
    b = None
    if bias_attr is not False:
        ba = {} if bias_attr in (None, True) else dict(bias_attr)
        b = helper.create_parameter(ba, [num_classes - 1], input.dtype,
                                    is_bias=True, suffix="b")
    out = helper.create_tmp_variable(input.dtype)
    pre_out = helper.create_tmp_variable(input.dtype, stop_gradient=True)
    inputs = {"X": [input.name], "Label": [label.name], "W": [w.name]}
    if b is not None:
        inputs["Bias"] = [b.name]
    helper.append_op("hsigmoid", inputs,
                     {"Out": [out.name], "PreOut": [pre_out.name]},
                     {"num_classes": int(num_classes)})
    out.shape = (-1, 1)
    return out


def auc(input, label, curve="ROC", num_thresholds=200):
    """Area-under-curve metric from prediction scores (reference
    layers auc / auc_op.cc; the kernel reads raw scores, so no top_k
    pre-pass is emitted)."""
    helper = LayerHelper("auc")
    auc_out = helper.create_tmp_variable("float32", stop_gradient=True)
    helper.append_op(
        "auc",
        {"Out": [input.name], "Label": [label.name]},
        {"AUC": [auc_out.name]},
        {"curve": curve, "num_thresholds": num_thresholds})
    return auc_out


def edit_distance(input, label, normalized=False, ignored_tokens=None):
    """Levenshtein distance between hypothesis and reference sequences
    (reference layers edit_distance / edit_distance_op.cc)."""
    helper = LayerHelper("edit_distance")
    if ignored_tokens:
        erased = helper.create_tmp_variable("int64")
        helper.append_op("sequence_erase", {"X": [input.name]},
                         {"Out": [erased.name]},
                         {"tokens": list(ignored_tokens)})
        input = erased
        erased_l = helper.create_tmp_variable("int64")
        helper.append_op("sequence_erase", {"X": [label.name]},
                         {"Out": [erased_l.name]},
                         {"tokens": list(ignored_tokens)})
        label = erased_l
    out = helper.create_tmp_variable("float32", stop_gradient=True)
    seq_num = helper.create_tmp_variable("int64", stop_gradient=True)
    helper.append_op(
        "edit_distance",
        {"Hyps": [input.name], "Refs": [label.name]},
        {"Out": [out.name], "SequenceNum": [seq_num.name]},
        {"normalized": bool(normalized)})
    return out, seq_num


def chunk_eval(input, label, chunk_scheme, num_chunk_types,
               excluded_chunk_types=None):
    helper = LayerHelper("chunk_eval")
    precision = helper.create_tmp_variable("float32", stop_gradient=True)
    recall = helper.create_tmp_variable("float32", stop_gradient=True)
    f1 = helper.create_tmp_variable("float32", stop_gradient=True)
    n_infer = helper.create_tmp_variable("int64", stop_gradient=True)
    n_label = helper.create_tmp_variable("int64", stop_gradient=True)
    n_correct = helper.create_tmp_variable("int64", stop_gradient=True)
    helper.append_op(
        "chunk_eval",
        {"Inference": [input.name], "Label": [label.name]},
        {"Precision": [precision.name], "Recall": [recall.name],
         "F1-Score": [f1.name], "NumInferChunks": [n_infer.name],
         "NumLabelChunks": [n_label.name],
         "NumCorrectChunks": [n_correct.name]},
        {"chunk_scheme": chunk_scheme, "num_chunk_types": num_chunk_types,
         "excluded_chunk_types": excluded_chunk_types or []})
    return precision, recall, f1, n_infer, n_label, n_correct


def _check_layout(value, name="data_format"):
    """Normalize/validate a layout string — a typo like "nhwc" silently
    building a mixed-layout network is the failure mode this closes."""
    v = str(value).upper()
    if v not in ("NCHW", "NHWC"):
        raise ValueError(f"{name} must be 'NCHW' or 'NHWC', got {value!r}")
    return v


def conv2d(input, num_filters, filter_size, stride=1, padding=0, dilation=1,
           groups=None, param_attr=None, bias_attr=None, act=None,
           name=None, use_cudnn=True, main_program=None,
           startup_program=None, data_format="NCHW"):
    helper = LayerHelper("conv2d", input=input, param_attr=param_attr,
                         bias_attr=bias_attr, act=act, name=name,
                         main_program=main_program,
                         startup_program=startup_program)
    dtype = input.dtype
    data_format = _check_layout(data_format)
    c_axis = 3 if data_format == "NHWC" else 1
    num_channels = input.shape[c_axis]
    groups = groups or 1
    if isinstance(filter_size, int):
        filter_size = [filter_size, filter_size]
    stride = [stride, stride] if isinstance(stride, int) else list(stride)
    padding = [padding, padding] if isinstance(padding, int) else list(padding)
    dilation = ([dilation, dilation] if isinstance(dilation, int)
                else list(dilation))
    filter_shape = [num_filters, num_channels // groups] + list(filter_size)
    import math

    fan_in = num_channels * filter_size[0] * filter_size[1]
    std = math.sqrt(2.0 / fan_in)
    from ..initializer import NormalInitializer

    w = helper.create_parameter(param_attr, filter_shape, dtype,
                                default_initializer=NormalInitializer(
                                    0.0, std),
                                suffix="w")
    pre_bias = helper.create_tmp_variable(dtype)
    helper.append_op(
        "conv2d", {"Input": [input.name], "Filter": [w.name]},
        {"Output": [pre_bias.name]},
        {"strides": stride, "paddings": padding, "dilations": dilation,
         "groups": groups, "use_cudnn": use_cudnn,
         "data_format": data_format})
    pre_act = helper.append_bias_op(pre_bias, dim_start=c_axis,
                                    dim_end=c_axis + 1)
    return helper.append_activation(pre_act)


def conv2d_transpose(input, num_filters, output_size=None, filter_size=None,
                     padding=0, stride=1, dilation=1, param_attr=None,
                     bias_attr=None, act=None, name=None):
    helper = LayerHelper("conv2d_transpose", input=input,
                         param_attr=param_attr, bias_attr=bias_attr,
                         act=act, name=name)
    dtype = input.dtype
    num_channels = input.shape[1]
    stride = [stride, stride] if isinstance(stride, int) else list(stride)
    padding = [padding, padding] if isinstance(padding, int) else list(padding)
    if filter_size is None:
        h = input.shape[2]
        out_h = output_size[0] if isinstance(output_size, (list, tuple)) \
            else output_size
        filter_size = [out_h - (h - 1) * stride[0] + 2 * padding[0]] * 2
    elif isinstance(filter_size, int):
        filter_size = [filter_size, filter_size]
    filter_shape = [num_channels, num_filters] + list(filter_size)
    w = helper.create_parameter(param_attr, filter_shape, dtype, suffix="w")
    pre_bias = helper.create_tmp_variable(dtype)
    dilation = ([dilation, dilation] if isinstance(dilation, int)
                else list(dilation))
    helper.append_op(
        "conv2d_transpose", {"Input": [input.name], "Filter": [w.name]},
        {"Output": [pre_bias.name]},
        {"strides": stride, "paddings": padding, "dilations": dilation})
    pre_act = helper.append_bias_op(pre_bias, dim_start=1, dim_end=2)
    return helper.append_activation(pre_act)


def pool2d(input, pool_size=2, pool_type="max", pool_stride=1,
           pool_padding=0, global_pooling=False, use_cudnn=True, name=None,
           data_format="NCHW"):
    helper = LayerHelper("pool2d", name=name)
    data_format = _check_layout(data_format)
    if isinstance(pool_size, int):
        pool_size = [pool_size, pool_size]
    if isinstance(pool_stride, int):
        pool_stride = [pool_stride, pool_stride]
    if isinstance(pool_padding, int):
        pool_padding = [pool_padding, pool_padding]
    out = helper.create_tmp_variable(input.dtype)
    helper.append_op(
        "pool2d", {"X": [input.name]}, {"Out": [out.name]},
        {"pooling_type": pool_type, "ksize": list(pool_size),
         "strides": list(pool_stride), "paddings": list(pool_padding),
         "global_pooling": global_pooling, "use_cudnn": use_cudnn,
         "data_format": data_format})
    return out


def batch_norm(input, act=None, is_test=False, momentum=0.9, epsilon=1e-5,
               param_attr=None, bias_attr=None, data_layout="NCHW",
               name=None, moving_mean_name=None, moving_variance_name=None):
    helper = LayerHelper("batch_norm", param_attr=param_attr,
                         bias_attr=bias_attr, act=act, name=name)
    dtype = input.dtype
    data_layout = _check_layout(data_layout, "data_layout")
    c_axis = 1 if data_layout == "NCHW" else len(input.shape) - 1
    channels = input.shape[c_axis]
    scale = helper.create_parameter(
        param_attr, [channels], dtype,
        default_initializer=ConstantInitializer(1.0), suffix="scale")
    bias = helper.create_parameter(bias_attr or {}, [channels], dtype,
                                   is_bias=True, suffix="offset")
    mean = helper.create_parameter(
        {"name": moving_mean_name, "trainable": False}, [channels], dtype,
        default_initializer=ConstantInitializer(0.0), suffix="mean")
    variance = helper.create_parameter(
        {"name": moving_variance_name, "trainable": False}, [channels],
        dtype, default_initializer=ConstantInitializer(1.0), suffix="var")
    mean.stop_gradient = True
    variance.stop_gradient = True
    saved_mean = helper.create_tmp_variable(dtype, stop_gradient=True)
    saved_var = helper.create_tmp_variable(dtype, stop_gradient=True)
    out = helper.create_tmp_variable(dtype)
    helper.append_op(
        "batch_norm",
        {"X": [input.name], "Scale": [scale.name], "Bias": [bias.name],
         "Mean": [mean.name], "Variance": [variance.name]},
        {"Y": [out.name], "MeanOut": [mean.name],
         "VarianceOut": [variance.name], "SavedMean": [saved_mean.name],
         "SavedVariance": [saved_var.name]},
        {"momentum": momentum, "epsilon": epsilon, "is_test": is_test,
         "data_layout": data_layout})
    return helper.append_activation(out)


def layer_norm(input, scale=True, shift=True, begin_norm_axis=1,
               epsilon=1e-5, param_attr=None, bias_attr=None, act=None,
               name=None):
    helper = LayerHelper("layer_norm", param_attr=param_attr,
                         bias_attr=bias_attr, act=act, name=name)
    dtype = input.dtype
    import numpy as np

    norm_shape = [int(np.prod(input.shape[begin_norm_axis:]))]
    inputs = {"X": [input.name]}
    if scale:
        s = helper.create_parameter(
            param_attr, norm_shape, dtype,
            default_initializer=ConstantInitializer(1.0), suffix="scale")
        inputs["Scale"] = [s.name]
    if shift:
        b = helper.create_parameter(bias_attr or {}, norm_shape, dtype,
                                    is_bias=True, suffix="shift")
        inputs["Bias"] = [b.name]
    out = helper.create_tmp_variable(dtype)
    mean = helper.create_tmp_variable(dtype, stop_gradient=True)
    var = helper.create_tmp_variable(dtype, stop_gradient=True)
    helper.append_op("layer_norm", inputs,
                     {"Y": [out.name], "Mean": [mean.name],
                      "Variance": [var.name]},
                     {"epsilon": epsilon,
                      "begin_norm_axis": begin_norm_axis})
    return helper.append_activation(out)


def lrn(input, n=5, k=2.0, alpha=1e-4, beta=0.75, name=None):
    helper = LayerHelper("lrn", name=name)
    out = helper.create_tmp_variable(input.dtype)
    mid = helper.create_tmp_variable(input.dtype, stop_gradient=True)
    helper.append_op("lrn", {"X": [input.name]},
                     {"Out": [out.name], "MidOut": [mid.name]},
                     {"n": n, "k": k, "alpha": alpha, "beta": beta})
    return out


def mean(x, name=None):
    helper = LayerHelper("mean", name=name)
    out = helper.create_tmp_variable(x.dtype)
    helper.append_op("mean", {"X": [x.name]}, {"Out": [out.name]})
    return out


def mul(x, y, x_num_col_dims=1, y_num_col_dims=1):
    helper = LayerHelper("mul")
    out = helper.create_tmp_variable(x.dtype)
    helper.append_op("mul", {"X": [x.name], "Y": [y.name]},
                     {"Out": [out.name]},
                     {"x_num_col_dims": x_num_col_dims,
                      "y_num_col_dims": y_num_col_dims})
    return out


def matmul(x, y, transpose_x=False, transpose_y=False, name=None):
    helper = LayerHelper("matmul", name=name)
    out = helper.create_tmp_variable(x.dtype)
    helper.append_op("matmul", {"X": [x.name], "Y": [y.name]},
                     {"Out": [out.name]},
                     {"transpose_X": transpose_x,
                      "transpose_Y": transpose_y})
    return out


def _reduce(op_type, input, dim=None, keep_dim=False, name=None):
    helper = LayerHelper(op_type, name=name)
    out = helper.create_tmp_variable(input.dtype)
    attrs = {"keep_dim": keep_dim}
    if dim is None:
        attrs["reduce_all"] = True
        attrs["dim"] = [0]
    else:
        attrs["reduce_all"] = False
        attrs["dim"] = dim if isinstance(dim, (list, tuple)) else [dim]
    helper.append_op(op_type, {"X": [input.name]}, {"Out": [out.name]},
                     attrs)
    return out


def reduce_sum(input, dim=None, keep_dim=False, name=None):
    return _reduce("reduce_sum", input, dim, keep_dim, name)


def reduce_mean(input, dim=None, keep_dim=False, name=None):
    return _reduce("reduce_mean", input, dim, keep_dim, name)


def reduce_max(input, dim=None, keep_dim=False, name=None):
    return _reduce("reduce_max", input, dim, keep_dim, name)


def reduce_min(input, dim=None, keep_dim=False, name=None):
    return _reduce("reduce_min", input, dim, keep_dim, name)


def topk(input, k=1):
    helper = LayerHelper("top_k")
    values = helper.create_tmp_variable(input.dtype, stop_gradient=True)
    indices = helper.create_tmp_variable("int64", stop_gradient=True)
    helper.append_op("top_k", {"X": [input.name]},
                     {"Out": [values.name], "Indices": [indices.name]},
                     {"k": k})
    return values, indices


def softmax_with_cross_entropy(logits, label, soft_label=False):
    helper = LayerHelper("softmax_with_cross_entropy")
    softmax = helper.create_tmp_variable(logits.dtype)
    loss = helper.create_tmp_variable(logits.dtype)
    helper.append_op("softmax_with_cross_entropy",
                     {"Logits": [logits.name], "Label": [label.name]},
                     {"Softmax": [softmax.name], "Loss": [loss.name]},
                     {"soft_label": soft_label})
    return loss


def sigmoid_cross_entropy_with_logits(x, label):
    helper = LayerHelper("sigmoid_cross_entropy_with_logits")
    out = helper.create_tmp_variable(x.dtype)
    helper.append_op("sigmoid_cross_entropy_with_logits",
                     {"X": [x.name], "Label": [label.name]},
                     {"Out": [out.name]})
    return out


def split(input, num_or_sections, dim=-1):
    helper = LayerHelper("split")
    dim = dim if dim >= 0 else dim + len(input.shape)
    if isinstance(num_or_sections, int):
        num = num_or_sections
        sections = []
    else:
        num = 0
        sections = list(num_or_sections)
    n_out = num if num else len(sections)
    outs = [helper.create_tmp_variable(input.dtype) for _ in range(n_out)]
    helper.append_op("split", {"X": [input.name]},
                     {"Out": [o.name for o in outs]},
                     {"axis": dim, "num": num, "sections": sections})
    return outs


def l2_normalize(x, axis, epsilon=1e-12, name=None):
    from . import ops as _ops
    from .tensor import fill_constant  # noqa: F401

    helper = LayerHelper("l2_normalize", name=name)
    square = _ops.square(x)
    ssum = reduce_sum(square, dim=axis, keep_dim=True)
    helper2 = LayerHelper("l2_normalize")
    norm = helper2.create_tmp_variable(x.dtype)
    helper2.append_op("sqrt", {"X": [ssum.name]}, {"Out": [norm.name]})
    out = helper2.create_tmp_variable(x.dtype)
    helper2.append_op("elementwise_div", {"X": [x.name], "Y": [norm.name]},
                      {"Out": [out.name]}, {"axis": 0})
    return out


def one_hot(input, depth):
    helper = LayerHelper("one_hot")
    out = helper.create_tmp_variable("float32", stop_gradient=True)
    helper.append_op("one_hot", {"X": [input.name]}, {"Out": [out.name]},
                     {"depth": depth, "dtype": "float32"})
    return out


def autoincreased_step_counter(counter_name=None, begin=1, step=1):
    """Persistable int64 step counter incremented every run
    (reference layers/nn.py autoincreased_step_counter)."""
    helper = LayerHelper("global_step_counter")
    name = counter_name or "@STEP_COUNTER@"
    counter = helper.main_program.global_block().create_var(
        name=name, dtype="int64", shape=(1,), persistable=True,
        stop_gradient=True)
    sb = helper.startup_program.global_block()
    if name not in sb.vars:
        sb.create_var(name=name, dtype="int64", shape=(1,),
                      persistable=True)
        sb.append_op("fill_constant", {}, {"Out": [name]},
                     {"shape": [1], "dtype": "int64",
                      "value": float(begin - step)})
    helper.append_op("increment", {"X": [name]}, {"Out": [name]},
                     {"step": float(step)})
    counter.stop_gradient = True
    return counter


def smooth_l1(x, y, inside_weight=None, outside_weight=None, sigma=None):
    helper = LayerHelper("smooth_l1")
    diff = helper.create_tmp_variable(x.dtype)
    out = helper.create_tmp_variable(x.dtype)
    inputs = {"X": [x.name], "Y": [y.name]}
    if inside_weight is not None:
        inputs["InsideWeight"] = [inside_weight.name]
    if outside_weight is not None:
        inputs["OutsideWeight"] = [outside_weight.name]
    helper.append_op("smooth_l1_loss", inputs,
                     {"Diff": [diff.name], "Out": [out.name]},
                     {"sigma": sigma or 1.0})
    return out


# ---------------------------------------------------------------------------
# sequence / recurrent layers (reference layers/nn.py dynamic_lstm :254,
# dynamic_gru :586, sequence_conv, sequence_pool, sequence_expand,
# sequence_softmax, sequence_first_step/last_step)
# ---------------------------------------------------------------------------


def dynamic_lstm(input, size, param_attr=None, bias_attr=None,
                 use_peepholes=True, is_reverse=False,
                 gate_activation="sigmoid", cell_activation="tanh",
                 candidate_activation="tanh", dtype="float32", name=None):
    """`input` must be a LoD var of width 4*hidden (typically an fc output);
    `size` is 4*hidden to match the reference API."""
    helper = LayerHelper("lstm", param_attr=param_attr,
                         bias_attr=bias_attr, name=name)
    hidden = size // 4
    weight = helper.create_parameter(param_attr, [hidden, 4 * hidden],
                                     dtype, suffix="w")
    bias_size = 7 * hidden if use_peepholes else 4 * hidden
    bias = helper.create_parameter(bias_attr or {}, [1, bias_size], dtype,
                                   is_bias=True, suffix="b")
    h = helper.create_tmp_variable(dtype)
    c = helper.create_tmp_variable(dtype)
    bg = helper.create_tmp_variable(dtype, stop_gradient=True)
    bc = helper.create_tmp_variable(dtype, stop_gradient=True)
    helper.append_op(
        "lstm",
        {"Input": [input.name], "Weight": [weight.name],
         "Bias": [bias.name]},
        {"Hidden": [h.name], "Cell": [c.name], "BatchGate": [bg.name],
         "BatchCellPreAct": [bc.name]},
        {"use_peepholes": use_peepholes, "is_reverse": is_reverse,
         "gate_activation": gate_activation,
         "cell_activation": cell_activation,
         "candidate_activation": candidate_activation})
    for v in (h, c):
        v.shape = (-1, hidden)
        v.lod_level = input.lod_level
    return h, c


def dynamic_gru(input, size, param_attr=None, bias_attr=None,
                is_reverse=False, gate_activation="sigmoid",
                candidate_activation="tanh", h_0=None, dtype="float32"):
    """`input` width must be 3*size."""
    helper = LayerHelper("gru", param_attr=param_attr, bias_attr=bias_attr)
    weight = helper.create_parameter(param_attr, [size, 3 * size], dtype,
                                     suffix="w")
    bias = helper.create_parameter(bias_attr or {}, [1, 3 * size], dtype,
                                   is_bias=True, suffix="b")
    h = helper.create_tmp_variable(dtype)
    inputs = {"Input": [input.name], "Weight": [weight.name],
              "Bias": [bias.name]}
    if h_0 is not None:
        inputs["H0"] = [h_0.name]
    bg = helper.create_tmp_variable(dtype, stop_gradient=True)
    br = helper.create_tmp_variable(dtype, stop_gradient=True)
    bh = helper.create_tmp_variable(dtype, stop_gradient=True)
    helper.append_op(
        "gru", inputs,
        {"Hidden": [h.name], "BatchGate": [bg.name],
         "BatchResetHiddenPrev": [br.name], "BatchHidden": [bh.name]},
        {"is_reverse": is_reverse, "gate_activation": gate_activation,
         "activation": candidate_activation})
    h.shape = (-1, size)
    h.lod_level = input.lod_level
    return h


def sequence_conv(input, num_filters, filter_size=3, filter_stride=1,
                  padding=None, bias_attr=None, param_attr=None, act=None):
    helper = LayerHelper("sequence_conv", input=input,
                         param_attr=param_attr, bias_attr=bias_attr,
                         act=act)
    dtype = input.dtype
    filter_shape = [filter_size * input.shape[-1], num_filters]
    w = helper.create_parameter(param_attr, filter_shape, dtype, suffix="w")
    pre_bias = helper.create_tmp_variable(dtype)
    helper.append_op(
        "sequence_conv",
        {"X": [input.name], "Filter": [w.name]},
        {"Out": [pre_bias.name]},
        {"contextStride": filter_stride,
         "contextStart": -int(filter_size // 2),
         "contextLength": filter_size})
    pre_bias.shape = (-1, num_filters)
    pre_bias.lod_level = input.lod_level
    pre_act = helper.append_bias_op(pre_bias)
    out = helper.append_activation(pre_act)
    out.lod_level = input.lod_level
    return out


def sequence_pool(input, pool_type):
    helper = LayerHelper("sequence_pool")
    out = helper.create_tmp_variable(input.dtype)
    max_index = helper.create_tmp_variable("int32", stop_gradient=True)
    helper.append_op("sequence_pool", {"X": [input.name]},
                     {"Out": [out.name], "MaxIndex": [max_index.name]},
                     {"pooltype": pool_type.upper()})
    out.shape = (-1,) + tuple(input.shape[1:])
    out.lod_level = max(0, input.lod_level - 1)
    return out


def sequence_first_step(input):
    return sequence_pool(input, "first")


def sequence_last_step(input):
    return sequence_pool(input, "last")


def sequence_softmax(input):
    helper = LayerHelper("sequence_softmax")
    out = helper.create_tmp_variable(input.dtype)
    helper.append_op("sequence_softmax", {"X": [input.name]},
                     {"Out": [out.name]})
    out.shape = input.shape
    out.lod_level = input.lod_level
    return out


def sequence_expand(x, y, name=None):
    helper = LayerHelper("sequence_expand", name=name)
    out = helper.create_tmp_variable(x.dtype)
    helper.append_op("sequence_expand", {"X": [x.name], "Y": [y.name]},
                     {"Out": [out.name]})
    out.shape = x.shape
    out.lod_level = max(x.lod_level, 1)
    return out


def sequence_reshape(input, new_dim):
    helper = LayerHelper("sequence_reshape")
    out = helper.create_tmp_variable(input.dtype)
    helper.append_op("sequence_reshape", {"X": [input.name]},
                     {"Out": [out.name]}, {"new_dim": new_dim})
    out.shape = (-1, new_dim)
    out.lod_level = input.lod_level
    return out


def lod_reset(x, y=None, target_lod=None):
    helper = LayerHelper("lod_reset")
    out = helper.create_tmp_variable(x.dtype)
    inputs = {"X": [x.name]}
    if y is not None:
        inputs["Y"] = [y.name]
    helper.append_op("lod_reset", inputs, {"Out": [out.name]},
                     {"target_lod": target_lod or []})
    out.shape = x.shape
    out.lod_level = max(1, x.lod_level)
    return out


def im2sequence(input, filter_size=1, stride=1, padding=0):
    helper = LayerHelper("im2sequence")
    fs = [filter_size] * 2 if isinstance(filter_size, int) else filter_size
    st = [stride] * 2 if isinstance(stride, int) else stride
    pd = [padding] * 4 if isinstance(padding, int) else padding
    out = helper.create_tmp_variable(input.dtype)
    helper.append_op("im2sequence", {"X": [input.name]},
                     {"Out": [out.name]},
                     {"kernels": list(fs), "strides": list(st),
                      "paddings": list(pd)})
    out.lod_level = 1
    return out


def dynamic_lstmp(input, size, proj_size, param_attr=None, bias_attr=None,
                  use_peepholes=True, is_reverse=False,
                  gate_activation="sigmoid", cell_activation="tanh",
                  candidate_activation="tanh", proj_activation="tanh",
                  dtype="float32", name=None):
    """LSTM with recurrent projection (reference layers/nn.py:400
    dynamic_lstmp / lstmp_op.cc).  `input` is a LoD var of width 4*hidden;
    `size` = 4*hidden, `proj_size` = projection width."""
    helper = LayerHelper("lstmp", param_attr=param_attr,
                         bias_attr=bias_attr, name=name)
    hidden = size // 4
    weight = helper.create_parameter(param_attr, [proj_size, 4 * hidden],
                                     dtype, suffix="w")
    proj_weight = helper.create_parameter(param_attr,
                                          [hidden, proj_size], dtype,
                                          suffix="proj_w")
    bias_size = 7 * hidden if use_peepholes else 4 * hidden
    bias = helper.create_parameter(bias_attr or {}, [1, bias_size], dtype,
                                   is_bias=True, suffix="b")
    proj = helper.create_tmp_variable(dtype)
    cell = helper.create_tmp_variable(dtype)
    bg = helper.create_tmp_variable(dtype, stop_gradient=True)
    bh = helper.create_tmp_variable(dtype, stop_gradient=True)
    bc = helper.create_tmp_variable(dtype, stop_gradient=True)
    helper.append_op(
        "lstmp",
        {"Input": [input.name], "Weight": [weight.name],
         "ProjWeight": [proj_weight.name], "Bias": [bias.name]},
        {"Projection": [proj.name], "Cell": [cell.name],
         "BatchGate": [bg.name], "BatchHidden": [bh.name],
         "BatchCellPreAct": [bc.name]},
        {"use_peepholes": use_peepholes, "is_reverse": is_reverse,
         "gate_activation": gate_activation,
         "cell_activation": cell_activation,
         "candidate_activation": candidate_activation,
         "proj_activation": proj_activation})
    return proj, cell


def gru_unit(input, hidden, size, weight=None, bias=None, param_attr=None,
             bias_attr=None, activation="tanh",
             gate_activation="sigmoid"):
    """Single GRU step (reference layers/nn.py:693 / gru_unit_op.cc);
    `input` is the projected gate input of width `size` (= 3*hidden)."""
    helper = LayerHelper("gru_unit", param_attr=param_attr,
                         bias_attr=bias_attr)
    dtype = input.dtype
    h = size // 3
    if weight is None:
        weight = helper.create_parameter(param_attr, [h, 3 * h], dtype,
                                         suffix="w")
    if bias is None:
        bias = helper.create_parameter(bias_attr or {}, [1, 3 * h], dtype,
                                       is_bias=True, suffix="b")
    gate = helper.create_tmp_variable(dtype)
    reset_hidden_pre = helper.create_tmp_variable(dtype)
    updated_hidden = helper.create_tmp_variable(dtype)
    helper.append_op(
        "gru_unit",
        {"Input": [input.name], "HiddenPrev": [hidden.name],
         "Weight": [weight.name], "Bias": [bias.name]},
        {"Gate": [gate.name], "ResetHiddenPrev": [reset_hidden_pre.name],
         "Hidden": [updated_hidden.name]},
        {"activation": activation, "gate_activation": gate_activation})
    return updated_hidden, reset_hidden_pre, gate


def lstm_unit(x_t, hidden_t_prev, cell_t_prev, forget_bias=0.0,
              param_attr=None, bias_attr=None, name=None):
    """Single LSTM step (reference layers/nn.py:1942 / lstm_unit_op.cc):
    gates = fc([x_t, h_prev]); returns (h, c)."""
    helper = LayerHelper("lstm_unit", param_attr=param_attr,
                         bias_attr=bias_attr, name=name)
    from .tensor import concat
    dtype = x_t.dtype
    size = hidden_t_prev.shape[-1]
    concat_out = concat([x_t, hidden_t_prev], axis=1)
    fc_out = fc(input=concat_out, size=4 * size, param_attr=param_attr,
                bias_attr=bias_attr)
    c = helper.create_tmp_variable(dtype)
    h = helper.create_tmp_variable(dtype)
    helper.append_op(
        "lstm_unit",
        {"X": [fc_out.name], "C_prev": [cell_t_prev.name]},
        {"C": [c.name], "H": [h.name]},
        {"forget_bias": float(forget_bias)})
    return h, c


def row_conv(input, future_context_size, param_attr=None, act=None):
    """Lookahead row convolution (reference layers/nn.py:2993 /
    row_conv_op.cc)."""
    helper = LayerHelper("row_conv", param_attr=param_attr, act=act)
    dtype = input.dtype
    filter_shape = [future_context_size + 1, input.shape[-1]]
    w = helper.create_parameter(param_attr, filter_shape, dtype, suffix="w")
    out = helper.create_tmp_variable(dtype)
    helper.append_op("row_conv",
                     {"X": [input.name], "Filter": [w.name]},
                     {"Out": [out.name]}, {})
    return helper.append_activation(out)


def multiplex(inputs, index):
    """Row-wise select across candidate tensors (reference multiplex_op)."""
    helper = LayerHelper("multiplex")
    out = helper.create_tmp_variable(inputs[0].dtype)
    helper.append_op(
        "multiplex",
        {"Ids": [index.name], "X": [v.name for v in inputs]},
        {"Out": [out.name]}, {})
    return out


def ctc_greedy_decoder(input, blank, name=None):
    """Per-step argmax then CTC collapse (reference layers/nn.py:2579:
    top_k(k=1) + ctc_align merge_repeated + blank removal)."""
    helper = LayerHelper("ctc_greedy_decoder", name=name)
    _, ids = topk(input, k=1)
    out = helper.create_tmp_variable("int64")
    out.lod_level = 1
    helper.append_op(
        "ctc_align", {"Input": [ids.name]}, {"Output": [out.name]},
        {"blank": blank, "merge_repeated": True})
    return out
