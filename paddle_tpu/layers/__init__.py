"""Layer DSL — fluid.layers equivalent surface."""
from .. import ops as _ops  # noqa: F401  (registers op lowerings)
from .io import *  # noqa: F401,F403
from .nn import *  # noqa: F401,F403
from .ops import *  # noqa: F401,F403
from .tensor import *  # noqa: F401,F403
from .control_flow import *  # noqa: F401,F403  (shadows ops.less_than etc.)
from .detection import *  # noqa: F401,F403
from .dist import *  # noqa: F401,F403
