"""Detection layer DSL: the SSD toolchain.

Reference: /root/reference/python/paddle/v2/fluid/layers/detection.py
(detection_output :44, prior_box :135, bipartite_match :340,
target_assign :398, ssd_loss :470) plus auto-wrapped ops (iou_similarity,
box_coder, multiclass_nms, mine_hard_examples, roi_pool, detection_map).
"""
from __future__ import annotations

from ..layer_helper import LayerHelper
from . import nn, ops, tensor

__all__ = [
    "prior_box",
    "prior_box_single",
    "box_coder",
    "iou_similarity",
    "bipartite_match",
    "target_assign",
    "mine_hard_examples",
    "multiclass_nms",
    "detection_output",
    "ssd_loss",
    "roi_pool",
    "detection_map",
]


def iou_similarity(x, y):
    helper = LayerHelper("iou_similarity")
    out = helper.create_tmp_variable(x.dtype)
    helper.append_op("iou_similarity", {"X": [x.name], "Y": [y.name]},
                     {"Out": [out.name]})
    return out


def box_coder(prior_box, prior_box_var, target_box,
              code_type="encode_center_size"):
    helper = LayerHelper("box_coder")
    out = helper.create_tmp_variable(target_box.dtype)
    helper.append_op(
        "box_coder",
        {"PriorBox": [prior_box.name], "PriorBoxVar": [prior_box_var.name],
         "TargetBox": [target_box.name]},
        {"OutputBox": [out.name]}, {"code_type": code_type})
    return out


def bipartite_match(dist_matrix, name=None):
    helper = LayerHelper("bipartite_match", name=name)
    match_indices = helper.create_tmp_variable("int32", stop_gradient=True)
    match_dist = helper.create_tmp_variable(dist_matrix.dtype,
                                            stop_gradient=True)
    helper.append_op(
        "bipartite_match", {"DistMat": [dist_matrix.name]},
        {"ColToRowMatchIndices": [match_indices.name],
         "ColToRowMatchDist": [match_dist.name]})
    return match_indices, match_dist


def target_assign(input, matched_indices, negative_indices=None,
                  mismatch_value=None, name=None):
    helper = LayerHelper("target_assign", name=name)
    out = helper.create_tmp_variable(input.dtype)
    out_weight = helper.create_tmp_variable("float32")
    inputs = {"X": [input.name], "MatchIndices": [matched_indices.name]}
    if negative_indices is not None:
        inputs["NegIndices"] = [negative_indices.name]
    helper.append_op("target_assign", inputs,
                     {"Out": [out.name], "OutWeight": [out_weight.name]},
                     {"mismatch_value": int(mismatch_value or 0)})
    return out, out_weight


def mine_hard_examples(cls_loss, match_indices, match_dist, loc_loss=None,
                       neg_pos_ratio=3.0, neg_dist_threshold=0.5,
                       mining_type="max_negative", sample_size=0):
    helper = LayerHelper("mine_hard_examples")
    neg_indices = helper.create_tmp_variable("int32", stop_gradient=True)
    neg_indices.lod_level = 1
    updated = helper.create_tmp_variable(match_indices.dtype,
                                         stop_gradient=True)
    inputs = {"ClsLoss": [cls_loss.name],
              "MatchIndices": [match_indices.name],
              "MatchDist": [match_dist.name]}
    if loc_loss is not None:
        inputs["LocLoss"] = [loc_loss.name]
    helper.append_op(
        "mine_hard_examples", inputs,
        {"NegIndices": [neg_indices.name],
         "UpdatedMatchIndices": [updated.name]},
        {"neg_pos_ratio": float(neg_pos_ratio),
         "neg_dist_threshold": float(neg_dist_threshold),
         "mining_type": mining_type, "sample_size": int(sample_size)})
    return neg_indices, updated


def multiclass_nms(bboxes, scores, background_label=0, score_threshold=0.01,
                   nms_top_k=400, nms_threshold=0.3, nms_eta=1.0,
                   keep_top_k=200):
    helper = LayerHelper("multiclass_nms")
    out = helper.create_tmp_variable(bboxes.dtype)
    out.lod_level = 1
    helper.append_op(
        "multiclass_nms",
        {"BBoxes": [bboxes.name], "Scores": [scores.name]},
        {"Out": [out.name]},
        {"background_label": int(background_label),
         "score_threshold": float(score_threshold),
         "nms_top_k": int(nms_top_k), "nms_threshold": float(nms_threshold),
         "nms_eta": float(nms_eta), "keep_top_k": int(keep_top_k)})
    return out


def prior_box_single(input, image, min_sizes, max_sizes=None,
                     aspect_ratios=None, variance=(0.1, 0.1, 0.2, 0.2),
                     flip=True, clip=True, steps=(0.0, 0.0), offset=0.5,
                     name=None):
    """One feature map -> (boxes, variances) [H, W, np, 4]
    (prior_box_op.cc)."""
    helper = LayerHelper("prior_box", name=name)
    boxes = helper.create_tmp_variable(input.dtype, stop_gradient=True)
    variances = helper.create_tmp_variable(input.dtype, stop_gradient=True)
    helper.append_op(
        "prior_box",
        {"Input": [input.name], "Image": [image.name]},
        {"Boxes": [boxes.name], "Variances": [variances.name]},
        {"min_sizes": [float(s) for s in min_sizes],
         "max_sizes": [float(s) for s in (max_sizes or [])],
         "aspect_ratios": [float(a) for a in (aspect_ratios or [1.0])],
         "variances": [float(v) for v in variance],
         "flip": bool(flip), "clip": bool(clip),
         "step_w": float(steps[0]), "step_h": float(steps[1]),
         "offset": float(offset)})
    return boxes, variances


def prior_box(inputs, image, min_ratio, max_ratio, aspect_ratios,
              base_size, steps=None, step_w=None, step_h=None, offset=0.5,
              variance=(0.1, 0.1, 0.2, 0.2), flip=True, clip=True,
              min_sizes=None, max_sizes=None, name=None):
    """Multi-feature-map SSD prior boxes, concatenated to [num_priors, 4]
    (reference layers/detection.py:135 prior_box / prior_boxes)."""
    assert isinstance(inputs, (list, tuple)) and inputs
    num_layer = len(inputs)
    if min_sizes is None or max_sizes is None:
        # reference ratio schedule: evenly spaced between min/max ratio
        min_sizes, max_sizes = [], []
        if num_layer > 2:
            step = int((max_ratio - min_ratio) / (num_layer - 2))
            for ratio in range(min_ratio, max_ratio + 1, step):
                min_sizes.append(base_size * ratio / 100.0)
                max_sizes.append(base_size * (ratio + step) / 100.0)
            min_sizes = [base_size * 0.1] + min_sizes
            max_sizes = [base_size * 0.2] + max_sizes
        else:
            min_sizes = [base_size * min_ratio / 100.0] * num_layer
            max_sizes = [base_size * max_ratio / 100.0] * num_layer
    box_results, var_results = [], []
    for i, inp in enumerate(inputs):
        ms = min_sizes[i]
        mx = max_sizes[i]
        ar = aspect_ratios[i] if isinstance(aspect_ratios[i], (list, tuple)) \
            else [aspect_ratios[i]]
        st = steps[i] if steps else (
            (step_w[i] if step_w else 0.0, step_h[i] if step_h else 0.0))
        b, v = prior_box_single(
            inp, image,
            min_sizes=[ms] if not isinstance(ms, (list, tuple)) else ms,
            max_sizes=[mx] if not isinstance(mx, (list, tuple)) else mx,
            aspect_ratios=ar, variance=variance, flip=flip, clip=clip,
            steps=st, offset=offset)
        box_results.append(ops.reshape(b, shape=[-1, 4]))
        var_results.append(ops.reshape(v, shape=[-1, 4]))
    if len(box_results) == 1:
        return box_results[0], var_results[0]
    return (tensor.concat(box_results, axis=0),
            tensor.concat(var_results, axis=0))


def detection_output(loc, scores, prior_box, prior_box_var,
                     background_label=0, nms_threshold=0.3, nms_top_k=400,
                     keep_top_k=200, score_threshold=0.01, nms_eta=1.0):
    """Decode loc deltas against priors + multiclass NMS (reference
    layers/detection.py:44): loc [N, M, 4], scores [N, C, M]."""
    helper = LayerHelper("detection_output")
    # decode per batch item: box_coder expects [row, 4] targets; use the
    # batched decode path [N, M, 4] treated row-wise
    decoded = helper.create_tmp_variable(loc.dtype)
    helper.append_op(
        "box_coder",
        {"PriorBox": [prior_box.name], "PriorBoxVar": [prior_box_var.name],
         "TargetBox": [loc.name]},
        {"OutputBox": [decoded.name]},
        {"code_type": "decode_center_size"})
    return multiclass_nms(decoded, scores, background_label=background_label,
                          score_threshold=score_threshold,
                          nms_top_k=nms_top_k, nms_threshold=nms_threshold,
                          nms_eta=nms_eta, keep_top_k=keep_top_k)


def ssd_loss(location, confidence, gt_box, gt_label, prior_box,
             prior_box_var=None, background_label=0, overlap_threshold=0.5,
             neg_pos_ratio=3.0, neg_overlap=0.5, sample_size=None,
             loc_loss_weight=1.0, conf_loss_weight=1.0,
             match_type="per_prediction", mining_type="max_negative"):
    """Weighted SSD localization + confidence loss (reference
    layers/detection.py:470) — iou match -> target assign -> hard negative
    mining -> smooth_l1 + softmax CE."""
    if mining_type != "max_negative":
        raise ValueError("Only mining_type == max_negative is supported")
    num, num_prior = location.shape[0], location.shape[1]

    # 1. match gt to priors
    iou = iou_similarity(x=gt_box, y=prior_box)
    matched_indices, matched_dist = bipartite_match(iou)

    # 2. confidence loss for mining
    lbl3 = ops.reshape(gt_label, shape=(-1, 1, 1))
    target_label, _ = target_assign(lbl3, matched_indices,
                                    mismatch_value=background_label)
    conf_2d = ops.reshape(confidence, shape=(-1, confidence.shape[-1]))
    tl_2d = tensor.cast(ops.reshape(target_label, shape=(-1, 1)), "int64")
    conf_loss = nn.softmax_with_cross_entropy(conf_2d, tl_2d)

    # 3. mine hard negatives
    conf_loss_nm = ops.reshape(conf_loss, shape=(num, num_prior))
    neg_indices, updated_indices = mine_hard_examples(
        conf_loss_nm, matched_indices, matched_dist,
        neg_pos_ratio=neg_pos_ratio, neg_dist_threshold=neg_overlap,
        mining_type=mining_type, sample_size=sample_size or 0)

    # 4. regression + classification targets
    encoded_bbox = box_coder(prior_box=prior_box,
                             prior_box_var=prior_box_var,
                             target_box=gt_box,
                             code_type="encode_center_size")
    target_bbox, target_loc_weight = target_assign(
        encoded_bbox, updated_indices, mismatch_value=background_label)
    target_label, target_conf_weight = target_assign(
        lbl3, updated_indices, negative_indices=neg_indices,
        mismatch_value=background_label)

    # 5. losses
    tl_2d = tensor.cast(ops.reshape(target_label, shape=(-1, 1)), "int64")
    conf_loss = nn.softmax_with_cross_entropy(conf_2d, tl_2d)
    conf_loss = conf_loss * ops.reshape(target_conf_weight, shape=(-1, 1))

    loc_2d = ops.reshape(location, shape=(-1, 4))
    tb_2d = ops.reshape(target_bbox, shape=(-1, 4))
    loc_loss = nn.smooth_l1(loc_2d, tb_2d)
    loc_loss = loc_loss * ops.reshape(target_loc_weight, shape=(-1, 1))

    loss = ops.scale(conf_loss, scale=float(conf_loss_weight))
    loss = loss + ops.scale(loc_loss, scale=float(loc_loss_weight))
    return loss


def roi_pool(input, rois, pooled_height=1, pooled_width=1,
             spatial_scale=1.0):
    helper = LayerHelper("roi_pool")
    out = helper.create_tmp_variable(input.dtype)
    argmax = helper.create_tmp_variable("int64", stop_gradient=True)
    helper.append_op(
        "roi_pool", {"X": [input.name], "ROIs": [rois.name]},
        {"Out": [out.name], "Argmax": [argmax.name]},
        {"pooled_height": int(pooled_height),
         "pooled_width": int(pooled_width),
         "spatial_scale": float(spatial_scale)})
    return out


def detection_map(detect_res, label, overlap_threshold=0.5,
                  evaluate_difficult=True, ap_type="integral"):
    helper = LayerHelper("detection_map")
    out = helper.create_tmp_variable("float32", stop_gradient=True)
    helper.append_op(
        "detection_map",
        {"DetectRes": [detect_res.name], "Label": [label.name]},
        {"MAP": [out.name]},
        {"overlap_threshold": float(overlap_threshold),
         "evaluate_difficult": bool(evaluate_difficult),
         "ap_type": ap_type})
    return out
