"""Control-flow DSL: While, StaticRNN, DynamicRNN, tensor arrays, LoD rank
tables, and beam-search wiring.

Reference: /root/reference/python/paddle/v2/fluid/layers/control_flow.py
(While :various, StaticRNN, DynamicRNN, array ops, lod_rank_table) — same
public API, but the recurrent constructs compile to the single scan-based
`dynamic_rnn` op (ops/control_flow.py) instead of while_op + tensor-array
plumbing, and `While` itself is the host-interpreted escape hatch used by
dynamic-shape decode loops (beam search).
"""
from __future__ import annotations

import contextlib

import numpy as np

from ..core.framework import Variable, unique_name
from ..core.types import VarType, is_float_dtype
from ..layer_helper import LayerHelper
from .tensor import fill_constant

__all__ = [
    "While",
    "Switch",
    "ConditionalBlock",
    "Print",
    "StaticRNN",
    "DynamicRNN",
    "IfElse",
    "ParallelDo",
    "get_places",
    "split_lod_tensor",
    "merge_lod_tensor",
    "less_than",
    "equal",
    "increment",
    "array_write",
    "array_read",
    "array_length",
    "create_array",
    "lod_rank_table",
    "max_sequence_len",
    "lod_tensor_to_array",
    "array_to_lod_tensor",
    "shrink_memory",
    "reorder_lod_tensor_by_rank",
    "beam_search",
    "beam_search_decode",
    "recompute",
]


# ---------------------------------------------------------------------------
# small wrappers (cond-style outputs)
# ---------------------------------------------------------------------------


def less_than(x, y, cond=None, **ignored):
    """x < y elementwise; writes into `cond` if given (reference
    layers.less_than with the in-place cond idiom used by While loops)."""
    helper = LayerHelper("less_than")
    if cond is None:
        cond = helper.create_tmp_variable("bool")
        cond.stop_gradient = True
    helper.append_op("less_than", {"X": [x.name], "Y": [y.name]},
                     {"Out": [cond.name]})
    return cond


def equal(x, y, cond=None, **ignored):
    helper = LayerHelper("equal")
    if cond is None:
        cond = helper.create_tmp_variable("bool")
        cond.stop_gradient = True
    helper.append_op("equal", {"X": [x.name], "Y": [y.name]},
                     {"Out": [cond.name]})
    return cond


# re-exported so `layers.increment` keeps working after the wildcard import
# of this module shadows layers.tensor — single definition lives there
from .tensor import increment  # noqa: E402,F401


# ---------------------------------------------------------------------------
# tensor arrays
# ---------------------------------------------------------------------------


def create_array(dtype):
    helper = LayerHelper("array")
    return helper.block.create_var(
        name=unique_name("array"), dtype=dtype,
        type=VarType.LOD_TENSOR_ARRAY)


def array_write(x, i, array=None):
    helper = LayerHelper("array_write")
    if array is None:
        array = create_array(x.dtype)
    if array.shape is None:
        array.shape = x.shape  # element shape hint for downstream layers
    helper.append_op("write_to_array",
                     {"X": [x.name], "I": [i.name]},
                     {"Out": [array.name]})
    return array


def array_read(array, i):
    helper = LayerHelper("array_read")
    out = helper.create_tmp_variable(array.dtype)
    out.shape = array.shape
    helper.append_op("read_from_array",
                     {"X": [array.name], "I": [i.name]},
                     {"Out": [out.name]})
    return out


def array_length(array):
    helper = LayerHelper("array_length")
    out = helper.create_tmp_variable("int64")
    out.stop_gradient = True
    helper.append_op("lod_array_length", {"X": [array.name]},
                     {"Out": [out.name]})
    return out


# ---------------------------------------------------------------------------
# LoD rank table machinery
# ---------------------------------------------------------------------------


def lod_rank_table(x, level=0):
    helper = LayerHelper("lod_rank_table")
    table = helper.block.create_var(
        name=unique_name("lod_rank_table"), dtype=None,
        type=VarType.LOD_RANK_TABLE)
    helper.append_op("lod_rank_table", {"X": [x.name]},
                     {"Out": [table.name]}, {"level": level})
    return table


def max_sequence_len(rank_table):
    helper = LayerHelper("max_seqence_len")
    out = helper.create_tmp_variable("int64", stop_gradient=True)
    helper.append_op("max_sequence_len", {"RankTable": [rank_table.name]},
                     {"Out": [out.name]})
    return out


def lod_tensor_to_array(x, table):
    helper = LayerHelper("lod_tensor_to_array")
    array = helper.block.create_var(
        name=unique_name("lod_tensor_to_array"), dtype=x.dtype,
        type=VarType.LOD_TENSOR_ARRAY)
    helper.append_op("lod_tensor_to_array",
                     {"X": [x.name], "RankTable": [table.name]},
                     {"Out": [array.name]})
    return array


def array_to_lod_tensor(x, table):
    helper = LayerHelper("array_to_lod_tensor")
    out = helper.create_tmp_variable(x.dtype)
    helper.append_op("array_to_lod_tensor",
                     {"X": [x.name], "RankTable": [table.name]},
                     {"Out": [out.name]})
    return out


def shrink_memory(x, i, table):
    helper = LayerHelper("shrink_memory")
    out = helper.create_tmp_variable(x.dtype)
    helper.append_op("shrink_rnn_memory",
                     {"X": [x.name], "I": [i.name],
                      "RankTable": [table.name]},
                     {"Out": [out.name]})
    return out


def reorder_lod_tensor_by_rank(x, rank_table):
    helper = LayerHelper("reorder_lod_tensor_by_rank")
    out = helper.create_tmp_variable(x.dtype)
    helper.append_op("reorder_lod_tensor_by_rank",
                     {"X": [x.name], "RankTable": [rank_table.name]},
                     {"Out": [out.name]})
    return out


# ---------------------------------------------------------------------------
# beam search
# ---------------------------------------------------------------------------


def beam_search(pre_ids, ids, scores, beam_size, end_id, level=0):
    """One beam-search step (reference layers.beam_search)."""
    helper = LayerHelper("beam_search")
    selected_ids = helper.create_tmp_variable("int64", stop_gradient=True)
    selected_scores = helper.create_tmp_variable("float32",
                                                 stop_gradient=True)
    selected_ids.lod_level = 2
    selected_scores.lod_level = 2
    helper.append_op(
        "beam_search",
        {"pre_ids": [pre_ids.name], "ids": [ids.name],
         "scores": [scores.name]},
        {"selected_ids": [selected_ids.name],
         "selected_scores": [selected_scores.name]},
        {"level": int(level), "beam_size": int(beam_size),
         "end_id": int(end_id)})
    return selected_ids, selected_scores


def beam_search_decode(ids, scores):
    helper = LayerHelper("beam_search_decode")
    sentence_ids = helper.create_tmp_variable("int64", stop_gradient=True)
    sentence_scores = helper.create_tmp_variable("float32",
                                                 stop_gradient=True)
    sentence_ids.lod_level = 2
    sentence_scores.lod_level = 2
    helper.append_op(
        "beam_search_decode",
        {"Ids": [ids.name], "Scores": [scores.name]},
        {"SentenceIds": [sentence_ids.name],
         "SentenceScores": [sentence_scores.name]})
    return sentence_ids, sentence_scores


# ---------------------------------------------------------------------------
# While
# ---------------------------------------------------------------------------


def get_places(device_count=None, device_type=None):
    """Materialize the device list (reference layers/device.py get_places /
    get_places_op.cc).  Returns jax devices rather than a Places variable —
    on a TPU mesh "places" are mesh coordinates, not program state."""
    from ..parallel.mesh import get_places as _mesh_places
    del device_type  # single accelerator type per process in jax
    return _mesh_places(device_count)


class ParallelDo:
    """Single-host data parallelism over a block (reference ParallelDo /
    parallel_do_op.cc:113).

    The reference splits the batch into per-place scopes and runs the block
    on worker threads, summing partial grads back to place 0.  Here the
    construct lowers to one `parallel_do` op that annotates its inputs with
    a batch sharding over a 'dp' device mesh and traces the block inline —
    XLA partitions forward AND backward across devices (the thread pool,
    scope copies, and AccumulateGrad sum all disappear into the partitioner).

    Usage (reference test_parallel_op.py shape):
        places = layers.get_places()
        pd = layers.ParallelDo(places)
        with pd.do():
            x_ = pd.read_input(x)
            hidden = layers.fc(input=x_, size=n)
            pd.write_output(hidden)
        out = pd()
    """

    def __init__(self, places, use_nccl=False, name=None):
        self.helper = LayerHelper("parallel_do", name=name)
        self.places = list(places)
        self.use_nccl = use_nccl
        self.sub = None
        self.parent = None
        self._inputs = []   # (parent var, placeholder)
        self._outputs = []  # sub-block vars
        self._result_vars = None
        self._finalized = False

    @contextlib.contextmanager
    def do(self):
        program = self.helper.main_program
        self.parent = program.current_block
        self.sub = program.create_block()
        try:
            yield
        finally:
            program.rollback()
        self._finalize()

    def read_input(self, var):
        assert self.sub is not None, "read_input must be called in do()"
        ph = self.sub.create_var(
            name=unique_name("pdo_in"), shape=var.shape, dtype=var.dtype)
        self._inputs.append((var, ph))
        return ph

    def write_output(self, var):
        self._outputs.append(var)

    def __call__(self):
        assert self._finalized, "use `with pd.do():` before pd()"
        if len(self._result_vars) == 1:
            return self._result_vars[0]
        return list(self._result_vars)

    def _captured_names(self):
        local = set(self.sub.vars.keys())
        captured = []
        for op in self.sub.ops:
            for n in op.input_names():
                if n in ("", "@EMPTY@") or n in local or n in captured:
                    continue
                if self.parent.has_var(n):
                    captured.append(n)
        return captured

    def _finalize(self):
        assert self._outputs, "parallel_do block must write_output"
        cap_f, cap_i = [], []
        for n in self._captured_names():
            v = self.parent.var(n)
            if v.dtype is not None and is_float_dtype(v.dtype):
                cap_f.append(n)
            else:
                cap_i.append(n)
        out_vars = [
            self.parent.create_var(name=unique_name("pdo_out"),
                                   shape=ov.shape, dtype=ov.dtype)
            for ov in self._outputs
        ]
        self.parent.append_op(
            "parallel_do",
            {"Inputs": [x.name for x, _ in self._inputs],
             "Captured": cap_f,
             "CapturedNoGrad": cap_i},
            {"Outs": [v.name for v in out_vars]},
            {"sub_block": {"__block__": self.sub.idx},
             "use_nccl": self.use_nccl,
             "num_places": len(self.places),
             "input_names": [p.name for _, p in self._inputs],
             "output_names": [v.name for v in self._outputs]})
        self._result_vars = out_vars
        self._finalized = True


def split_lod_tensor(input, mask, level=0):
    """Split `input` rows (or level-`level` sequences) into the true/false
    branches selected by the bool column `mask` (reference
    layers.split_lod_tensor / split_lod_tensor_op.cc)."""
    helper = LayerHelper("split_lod_tensor")
    out_true = helper.create_tmp_variable(dtype=input.dtype)
    out_false = helper.create_tmp_variable(dtype=input.dtype)
    helper.append_op(
        "split_lod_tensor",
        {"X": [input.name], "Mask": [mask.name]},
        {"OutTrue": [out_true.name], "OutFalse": [out_false.name]},
        {"level": level})
    return out_true, out_false


def merge_lod_tensor(in_true, in_false, x, mask, level=0):
    """Inverse of split_lod_tensor: interleave the branches back into `x`'s
    row order (reference layers.merge_lod_tensor)."""
    helper = LayerHelper("merge_lod_tensor")
    out = helper.create_tmp_variable(dtype=in_true.dtype)
    helper.append_op(
        "merge_lod_tensor",
        {"X": [x.name], "Mask": [mask.name],
         "InTrue": [in_true.name], "InFalse": [in_false.name]},
        {"Out": [out.name]},
        {"level": level})
    return out


class IfElse:
    """Batch-row conditional (reference layers.IfElse): rows where `cond`
    is true flow through the true block, the rest through the false block,
    and outputs are merged back into batch order.

    TPU-native design note: the reference wraps each branch in a
    ConditionalBlock sub-block; here branch ops are appended to the current
    block operating directly on the split row-subsets (a branch with zero
    selected rows simply computes on 0-row tensors).  That keeps the whole
    construct differentiable through split/merge grads and lets branch ops
    run in compiled segments keyed by the realized shapes."""

    OUT_IF_ELSE_BLOCKS = 0
    IN_IF_ELSE_TRUE_BLOCKS = 1
    IN_IF_ELSE_FALSE_BLOCKS = 2

    def __init__(self, cond, name=None):
        if not isinstance(cond, Variable):
            raise TypeError("cond must be a Variable")
        self.helper = LayerHelper("ifelse", name=name)
        self.cond = cond
        self.input_table = {}
        self.status = IfElse.OUT_IF_ELSE_BLOCKS
        self.output_table = ([], [])  # (false_outs, true_outs)

    def input(self, x):
        if self.status == IfElse.OUT_IF_ELSE_BLOCKS:
            raise ValueError("input() must be called inside a block")
        if id(x) not in self.input_table:
            self.input_table[id(x)] = split_lod_tensor(x, self.cond)
        out_true, out_false = self.input_table[id(x)]
        return (out_true if self.status == IfElse.IN_IF_ELSE_TRUE_BLOCKS
                else out_false)

    @contextlib.contextmanager
    def _block(self, is_true):
        if self.status != IfElse.OUT_IF_ELSE_BLOCKS:
            raise ValueError("cannot nest IfElse blocks")
        self.status = (IfElse.IN_IF_ELSE_TRUE_BLOCKS if is_true
                       else IfElse.IN_IF_ELSE_FALSE_BLOCKS)
        try:
            yield
        finally:
            self.status = IfElse.OUT_IF_ELSE_BLOCKS
        if len(self.output_table[1 if is_true else 0]) == 0:
            raise ValueError("must call output() inside the block")

    def true_block(self):
        return self._block(True)

    def false_block(self):
        return self._block(False)

    def output(self, *outs):
        if self.status == self.OUT_IF_ELSE_BLOCKS:
            raise ValueError("output() can only be called inside a block")
        table = self.output_table[
            1 if self.status == self.IN_IF_ELSE_TRUE_BLOCKS else 0]
        from .tensor import assign
        for each in outs:
            if not isinstance(each, Variable):
                raise TypeError("each output must be a Variable")
            table.append(assign(each))

    def __call__(self):
        if self.status != self.OUT_IF_ELSE_BLOCKS:
            raise ValueError("__call__ must be outside the blocks")
        false_len, true_len = map(len, self.output_table)
        if false_len == 0 and true_len == 0:
            raise ValueError("must call true_block/false_block before "
                             "__call__")
        if false_len != true_len and false_len != 0 and true_len != 0:
            raise ValueError("true/false blocks must set the same number "
                             "of outputs")
        if false_len == 0 or true_len == 0:
            return self.output_table[0 if false_len != 0 else 1]
        rlist = []
        for false_var, true_var in zip(*self.output_table):
            rlist.append(merge_lod_tensor(
                in_true=true_var, in_false=false_var,
                x=self.cond, mask=self.cond))
        return rlist


class While:
    """Host-interpreted while loop over a sub-block (reference
    layers.While / while_op.cc).  The body runs in the surrounding variable
    environment, so condition updates and array writes persist."""

    def __init__(self, cond, name=None):
        if cond.dtype not in ("bool",):
            raise TypeError("While condition must be a bool variable")
        self.cond = cond
        self.helper = LayerHelper("while", name=name)

    @contextlib.contextmanager
    def block(self):
        program = self.helper.main_program
        parent = program.current_block
        sub = program.create_block()
        try:
            yield
        finally:
            program.rollback()
        parent.append_op(
            "while",
            {"Condition": [self.cond.name], "X": []},
            {"Out": []},
            {"sub_block": {"__block__": sub.idx}})


# ---------------------------------------------------------------------------
# shared RNN builder (StaticRNN and DynamicRNN both emit `dynamic_rnn`)
# ---------------------------------------------------------------------------


class _RNNBase:
    _is_dynamic = True

    def __init__(self, name=None):
        self.helper = LayerHelper(
            "dynamic_rnn" if self._is_dynamic else "static_rnn", name=name)
        self.sub = None
        self.parent = None
        self._step_inputs = []     # (parent var, placeholder)
        self._static_inputs = []   # (parent var, placeholder)
        self._memories = []        # dicts: placeholder/init/shape/value/dtype
        self._mem_updates = {}     # placeholder name -> update var name
        self._outputs = []         # sub-block vars
        self._result_vars = None
        self._finalized = False

    @contextlib.contextmanager
    def block(self):
        program = self.helper.main_program
        self.parent = program.current_block
        self.sub = program.create_block()
        try:
            yield
        finally:
            program.rollback()
        self._finalize()  # only on clean exit — don't mask body errors

    # -- user API ------------------------------------------------------------
    def step_input(self, x):
        assert self.sub is not None, "step_input must be called in block()"
        # dynamic: per-step value is [B, ...feature]; static: axis 0 IS the
        # time axis, so a step sees exactly x.shape[1:]
        ph_shape = ((-1,) + tuple(x.shape[1:]) if self._is_dynamic
                    else tuple(x.shape[1:]))
        ph = self.sub.create_var(
            name=unique_name("rnn_step_in"), shape=ph_shape, dtype=x.dtype)
        self._step_inputs.append((x, ph))
        return ph

    def static_input(self, x):
        ph = self.sub.create_var(
            name=unique_name("rnn_static_in"),
            shape=x.shape, dtype=x.dtype)
        self._static_inputs.append((x, ph))
        return ph

    def memory(self, init=None, shape=None, value=0.0, dtype="float32",
               need_reorder=False, batch_ref=None, init_value=None,
               init_batch_dim_idx=0, ref_batch_dim_idx=1):
        if init is not None:
            ph = self.sub.create_var(
                name=unique_name("rnn_mem"),
                shape=init.shape, dtype=init.dtype)
            self._memories.append({"ph": ph, "init_var": init, "init": True})
        else:
            assert shape is not None, "memory needs init= or shape="
            if init_value is not None:
                value = init_value
            # dynamic: runtime value is [B, ...shape]; static: exactly shape
            mem_shape = ((-1,) + tuple(shape) if self._is_dynamic
                         else tuple(shape))
            ph = self.sub.create_var(
                name=unique_name("rnn_mem"), shape=mem_shape, dtype=dtype)
            self._memories.append({
                "ph": ph, "init_var": None, "init": False,
                "shape": [int(s) for s in shape], "value": float(value),
                "dtype": dtype})
        return ph

    def update_memory(self, mem, new):
        self._mem_updates[mem.name] = new.name

    def output(self, *outputs):
        self._outputs.extend(outputs)

    step_output = output

    def __call__(self, *a, **kw):
        assert self._finalized, "use `with rnn.block():` before rnn()"
        if len(self._result_vars) == 1:
            return self._result_vars[0]
        return list(self._result_vars)

    # -- finalization --------------------------------------------------------
    def _captured_names(self):
        local = set(self.sub.vars.keys())
        captured = []
        for op in self.sub.ops:
            for n in op.input_names():
                if n in ("", "@EMPTY@") or n in local or n in captured:
                    continue
                if self.parent.has_var(n):
                    captured.append(n)
        return captured

    def _finalize(self):
        assert self._outputs, "rnn block must declare at least one output"
        assert self._mem_updates.keys() == {
            m["ph"].name for m in self._memories
        }, "every memory needs exactly one update_memory call"
        cap_f, cap_i = [], []
        for n in self._captured_names():
            v = self.parent.var(n)
            if v.dtype is not None and is_float_dtype(v.dtype):
                cap_f.append(n)
            else:
                cap_i.append(n)
        mem_specs = []
        init_vars = []
        for m in self._memories:
            if m["init"]:
                mem_specs.append({"init": True})
                init_vars.append(m["init_var"].name)
            else:
                mem_specs.append({
                    "init": False, "shape": m["shape"],
                    "value": m["value"], "dtype": m["dtype"],
                    "batch_ref": self._is_dynamic})
        out_vars = []
        lod_level = (self._step_inputs[0][0].lod_level
                     if self._is_dynamic else 0)
        for ov in self._outputs:
            res = self.parent.create_var(
                name=unique_name("rnn_out"),
                shape=ov.shape, dtype=ov.dtype, lod_level=lod_level)
            out_vars.append(res)
        self.parent.append_op(
            "dynamic_rnn",
            {"StepInputs": [x.name for x, _ in self._step_inputs],
             "InitMemories": init_vars,
             "StaticInputs": [x.name for x, _ in self._static_inputs],
             "Captured": cap_f,
             "CapturedNoGrad": cap_i},
            {"Outs": [v.name for v in out_vars]},
            {"sub_block": {"__block__": self.sub.idx},
             "is_dynamic": self._is_dynamic,
             "step_input_names": [p.name for _, p in self._step_inputs],
             "static_input_names": [p.name for _, p in self._static_inputs],
             "memory_names": [m["ph"].name for m in self._memories],
             "memory_update_names": [
                 self._mem_updates[m["ph"].name] for m in self._memories],
             "memory_specs": mem_specs,
             "output_names": [v.name for v in self._outputs]})
        self._result_vars = out_vars
        self._finalized = True


class DynamicRNN(_RNNBase):
    """Variable-length RNN over LoD step inputs (reference
    layers/control_flow.py DynamicRNN).  Lowers to one lax.scan with
    padding+masking — see ops/control_flow.py dynamic_rnn."""

    _is_dynamic = True


class StaticRNN(_RNNBase):
    """Fixed-length RNN stepping axis 0 of dense inputs (reference
    recurrent_op.cc / layers StaticRNN)."""

    _is_dynamic = False

    @contextlib.contextmanager
    def step(self):
        with self.block():
            yield


class ConditionalBlock:
    """Thin wrapper over the conditional_block op (reference
    layers/control_flow.py ConditionalBlock / conditional_block_op.cc):
    runs the block iff every input is true/non-empty."""

    def __init__(self, inputs, name=None, is_scalar_condition=False):
        self.inputs = list(inputs)
        self.is_scalar_condition = is_scalar_condition
        self.helper = LayerHelper("conditional_block", name=name)

    @contextlib.contextmanager
    def block(self):
        program = self.helper.main_program
        parent = program.current_block
        sub = program.create_block()
        try:
            yield
        finally:
            program.rollback()
        parent.append_op(
            "conditional_block",
            {"X": [v.name for v in self.inputs], "Params": []},
            {"Out": []},
            {"sub_block": {"__block__": sub.idx},
             "is_scalar_condition": self.is_scalar_condition})


class Switch:
    """Scalar-condition switch/case chain (reference
    layers/control_flow.py Switch): the FIRST case whose condition is true
    runs; default() runs when none matched.

        with Switch() as switch:
            with switch.case(cond1): ...
            with switch.case(cond2): ...
            with switch.default(): ...
    """

    def __init__(self, name=None):
        self.helper = LayerHelper("switch", name=name)
        self.pre_not_conditions = []
        self.inside = False

    @contextlib.contextmanager
    def case(self, condition):
        if not self.inside:
            raise ValueError("case() must be inside `with Switch()`")
        from .ops import logical_and, logical_not
        if self.pre_not_conditions:
            pre = self.pre_not_conditions[-1]
            cond = logical_and(x=pre, y=condition)
        else:
            cond = condition
        not_cond = logical_not(x=condition)
        if self.pre_not_conditions:
            not_cond = logical_and(x=self.pre_not_conditions[-1],
                                   y=not_cond)
        self.pre_not_conditions.append(not_cond)
        cb = ConditionalBlock([cond], is_scalar_condition=True)
        with cb.block():
            yield

    @contextlib.contextmanager
    def default(self):
        if not self.pre_not_conditions:
            raise ValueError("default() requires at least one case()")
        cb = ConditionalBlock([self.pre_not_conditions[-1]],
                              is_scalar_condition=True)
        with cb.block():
            yield

    def __enter__(self):
        self.inside = True
        return self

    def __exit__(self, *a):
        self.inside = False
        return False


def Print(input, first_n=-1, message=None, summarize=-1,
          print_tensor_name=True, print_tensor_type=True,
          print_tensor_shape=True, print_tensor_lod=True,
          print_phase="both"):
    """Debug-print a tensor when it is executed (reference
    layers/control_flow.py:149 Print / print_op.cc)."""
    helper = LayerHelper("print")
    out = helper.create_tmp_variable(input.dtype)
    helper.append_op(
        "print", {"In": [input.name]}, {"Out": [out.name]},
        {"first_n": first_n, "message": message or "",
         "summarize": summarize,
         "print_tensor_name": print_tensor_name,
         "print_tensor_type": print_tensor_type,
         "print_tensor_shape": print_tensor_shape,
         "print_tensor_lod": print_tensor_lod,
         "print_phase": print_phase.upper()})
    return out


def recompute(fn, name=None):
    """Build `fn()`'s layers inside a rematerialized segment: activations
    in the segment are recomputed during backward instead of stored
    (lowering: jax.checkpoint over the sub-block — see ops/control_flow.py
    `recompute`).  `fn` takes no arguments, reads enclosing-scope
    Variables, and returns a Variable or list of Variables.

        h = fluid.layers.recompute(lambda: big_ffn_stack(x))

    No reference analogue; this is the HBM lever of the TPU build plan
    (SURVEY.md TPU notes) complementing `memory_optimize` (the reference's
    liveness transpiler).
    """
    helper = LayerHelper("recompute", name=name)
    program = helper.main_program
    parent = program.current_block
    sub = program.create_block()
    try:
        result = fn()
    finally:
        program.rollback()
    single = not isinstance(result, (list, tuple))
    out_vars = [result] if single else list(result)
    for v in out_vars:
        if v.name not in sub.vars:
            raise ValueError(
                f"recompute: output {v.name!r} was not produced inside "
                "the segment")

    # read-set: names referenced inside (recursively incl. nested
    # sub-blocks) but defined outside the segment
    reads, defined = [], set()

    def walk(block):
        defined.update(block.vars)
        for op in block.ops:
            for names in op.inputs.values():
                for n in names:
                    if n not in defined and n not in reads:
                        reads.append(n)
            for names in op.outputs.values():
                defined.update(names)
            for a in op.attrs.values():
                if isinstance(a, dict) and "__block__" in a:
                    walk(program.blocks[a["__block__"]])

    walk(sub)

    outs = []
    for v in out_vars:
        pv = parent.create_var(name=v.name, shape=v.shape, dtype=v.dtype,
                               lod_level=getattr(v, "lod_level", 0))
        pv.stop_gradient = v.stop_gradient
        outs.append(pv)
    # persistable writes inside the segment (BN running stats, counters)
    # must survive: without forwarding them the jax.checkpoint lowering
    # would silently drop the state updates of every rematerialized
    # batch_norm — carried as extra (non-user-visible) outputs
    result_names = {v.name for v in out_vars}
    state_writes = []

    def collect_state(block):
        # recurse like walk() above: a BN inside nested control flow
        # writes its stats from a deeper block
        for op_ in block.ops:
            for n in op_.output_names():
                try:
                    prog_var = block.var(n)  # ancestor-walking lookup
                except KeyError:
                    continue
                if (prog_var.persistable and n not in result_names
                        and n not in state_writes):
                    state_writes.append(n)
            for a in op_.attrs.values():
                if isinstance(a, dict) and "__block__" in a:
                    collect_state(program.blocks[a["__block__"]])

    collect_state(sub)
    parent.append_op(
        "recompute",
        {"X": reads},
        {"Out": [v.name for v in outs] + state_writes},
        {"sub_block": {"__block__": sub.idx},
         "output_names": [v.name for v in outs] + state_writes})
    return outs[0] if single else outs
