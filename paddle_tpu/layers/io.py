"""Data-declaration layer.

Reference: /root/reference/python/paddle/v2/fluid/layers/io.py (`data()`).
"""
from __future__ import annotations

from ..core.framework import default_main_program, default_startup_program

__all__ = ["data"]


def data(name, shape, dtype="float32", lod_level=0, append_batch_size=True,
         main_program=None, stop_gradient=True, type=None):
    """Declare a feed variable.  `append_batch_size=True` prepends -1,
    matching reference layers/io.py:data."""
    prog = main_program or default_main_program()
    shape = list(shape)
    if append_batch_size:
        shape = [-1] + shape
    kw = {}
    if type is not None:
        kw["type"] = type
    v = prog.global_block().create_var(
        name=name, shape=shape, dtype=dtype, lod_level=lod_level,
        stop_gradient=stop_gradient, **kw)
    # mirror the var desc into the startup program for symmetry
    default_startup_program()
    return v
