"""Data-declaration and distributed-IO layers.

Reference: /root/reference/python/paddle/v2/fluid/layers/io.py (`data()`,
`ListenAndServ`, `Send`, `Recv`).
"""
from __future__ import annotations

import contextlib

from ..core.framework import default_main_program, default_startup_program

__all__ = ["data", "ListenAndServ", "Send", "Recv"]


def data(name, shape, dtype="float32", lod_level=0, append_batch_size=True,
         main_program=None, stop_gradient=True, type=None, donate=False,
         sharding=None):
    """Declare a feed variable.  `append_batch_size=True` prepends -1,
    matching reference layers/io.py:data.

    `donate=True` marks the feed's device buffer as donatable to the
    jitted step (its HBM is reused for intermediates).  The hint is
    validated at build time: donating a buffer the caller still needs —
    e.g. a fetch target — raises `DonationError` before any tracing
    (memory_optimization_transpiler.plan_donation; the donation-safety
    analysis pass lints the same invariant).

    `sharding`: GSPMD-style per-dim mesh-axis annotation for multichip
    runs, e.g. `("dp", None)` to split the batch dim over the 'dp' mesh
    axis (docs/performance.md "Multichip sharding").  Inert under the
    serial executor; consumed by the spmd transpiler."""
    prog = main_program or default_main_program()
    shape = list(shape)
    if append_batch_size:
        shape = [-1] + shape
    kw = {}
    if type is not None:
        kw["type"] = type
    v = prog.global_block().create_var(
        name=name, shape=shape, dtype=dtype, lod_level=lod_level,
        stop_gradient=stop_gradient, donate=donate, sharding=sharding,
        **kw)
    # mirror the var desc into the startup program for symmetry
    default_startup_program()
    return v


class ListenAndServ:
    """Pserver-side block: serve variables, run the block as the optimize
    program after `fan_in` barriers (reference layers/io.py ListenAndServ /
    listen_and_serv_op.cc).

    Usage:
        serv = ListenAndServ("127.0.0.1:6174", fan_in=1)
        with serv.do():
            ...optimize ops on served vars...
        exe.run(main)   # blocks serving until a client sends STOP
    """

    def __init__(self, endpoint, inputs=None, fan_in=1, optimizer_mode=True,
                 sync_mode=True):
        self.endpoint = endpoint
        self.fan_in = fan_in
        self.inputs = inputs or []
        del optimizer_mode  # reference flag; the block is always the program
        # sync_mode=False: ASGD pserver (grads apply on arrival, no
        # barrier round — go/pserver semantics)
        self.sync_mode = sync_mode
        self.sub = None

    @contextlib.contextmanager
    def do(self):
        program = default_main_program()
        parent = program.current_block
        self.sub = program.create_block()
        try:
            yield
        finally:
            program.rollback()
        parent.append_op(
            "listen_and_serv",
            {"X": [v.name for v in self.inputs]},
            {},
            {"sub_block": {"__block__": self.sub.idx},
             "endpoint": self.endpoint,
             "Fanin": self.fan_in,
             "sync_mode": self.sync_mode})


def Send(endpoint, send_vars, get_vars, epmap=None, out_epmap=None):
    """Push `send_vars`, barrier, pull `get_vars` (reference layers Send /
    send_op.cc:44).  `endpoint` may be one 'host:port' or a list; with
    several, `epmap`/`out_epmap` route each var to its pserver.  An
    omitted `out_epmap` follows `epmap` when the arities line up (each
    param pulled from the server its grad went to — the transpiler
    pairing), else everything defaults to the first endpoint.  The
    runtime fuses each endpoint's vars into bucketed frames and serves
    endpoints concurrently (parallel/comm.py)."""
    eps = [endpoint] if isinstance(endpoint, str) else list(endpoint)
    epmap = list(epmap) if epmap else [eps[0]] * len(send_vars)
    if out_epmap:
        out_epmap = list(out_epmap)
    elif len(epmap) == len(get_vars):
        out_epmap = list(epmap)
    else:
        out_epmap = [eps[0]] * len(get_vars)
    helper_block = default_main_program().current_block
    helper_block.append_op(
        "send",
        {"X": [v.name for v in send_vars]},
        {"Out": [v.name for v in get_vars]},
        {"endpoints": eps,
         "epmap": epmap,
         "out_epmap": out_epmap})
    return get_vars


def Recv(endpoint, get_vars):
    """Fetch `get_vars` from `endpoint` (reference recv_op.cc:28)."""
    block = default_main_program().current_block
    block.append_op(
        "recv",
        {"X": []},
        {"Out": [v.name for v in get_vars]},
        {"endpoint": endpoint})
    return get_vars
