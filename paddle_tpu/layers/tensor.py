"""Tensor-creation / manipulation layers.

Reference: /root/reference/python/paddle/v2/fluid/layers/tensor.py.
"""
from __future__ import annotations

from ..core.framework import Variable
from ..layer_helper import LayerHelper

__all__ = [
    "create_tensor",
    "create_parameter",
    "create_global_var",
    "fill_constant",
    "fill_constant_batch_size_like",
    "ones",
    "zeros",
    "cast",
    "concat",
    "sums",
    "assign",
    "argmax",
    "increment",
    "zeros_like",
]


def create_tensor(dtype, name=None, main_program=None, persistable=False):
    helper = LayerHelper("create_tensor", name=name,
                         main_program=main_program)
    return helper.create_variable(
        name or helper.name, dtype=dtype, persistable=persistable)


def create_parameter(shape, dtype, name=None, attr=None, is_bias=False,
                     default_initializer=None):
    helper = LayerHelper("create_parameter", name=name)
    attr = dict(attr or {})
    if name:
        attr.setdefault("name", name)
    return helper.create_parameter(attr, shape, dtype, is_bias,
                                   default_initializer)


def create_global_var(shape, value, dtype, persistable=False, name=None):
    helper = LayerHelper("global_var", name=name)
    var = helper.create_global_variable(name=name, dtype=dtype, shape=shape,
                                        persistable=persistable)
    helper.startup_program.global_block().create_var(
        name=var.name, shape=tuple(shape), dtype=dtype, persistable=persistable)
    helper.startup_program.global_block().append_op(
        "fill_constant", {}, {"Out": [var.name]},
        {"shape": list(shape), "dtype": dtype, "value": float(value)})
    return var


def fill_constant(shape, dtype, value, out=None, main_program=None):
    helper = LayerHelper("fill_constant", main_program=main_program)
    if out is None:
        out = helper.create_tmp_variable(dtype=dtype)
    helper.append_op("fill_constant", {}, {"Out": [out.name]},
                     {"shape": list(shape), "dtype": dtype,
                      "value": float(value)})
    out.stop_gradient = True
    return out


def fill_constant_batch_size_like(input, shape, dtype, value,
                                  input_dim_idx=0, output_dim_idx=0):
    helper = LayerHelper("fill_constant_batch_size_like")
    out = helper.create_tmp_variable(dtype=dtype)
    helper.append_op("fill_constant_batch_size_like",
                     {"Input": [input.name]}, {"Out": [out.name]},
                     {"shape": list(shape), "dtype": dtype,
                      "value": float(value), "input_dim_idx": input_dim_idx,
                      "output_dim_idx": output_dim_idx})
    out.stop_gradient = True
    return out


def ones(shape, dtype, main_program=None):
    return fill_constant(shape, dtype, 1.0, main_program=main_program)


def zeros(shape, dtype, main_program=None):
    return fill_constant(shape, dtype, 0.0, main_program=main_program)


def cast(x, dtype):
    helper = LayerHelper("cast")
    out = helper.create_tmp_variable(dtype=dtype)
    helper.append_op("cast", {"X": [x.name]}, {"Out": [out.name]},
                     {"out_dtype": dtype})
    return out


def concat(input, axis=0):
    helper = LayerHelper("concat")
    out = helper.create_tmp_variable(dtype=input[0].dtype)
    helper.append_op("concat", {"X": [v.name for v in input]},
                     {"Out": [out.name]}, {"axis": axis})
    shapes = [v.shape for v in input]
    if all(s is not None for s in shapes):
        ax = axis if axis >= 0 else axis + len(shapes[0])
        dims = list(shapes[0])
        dims[ax] = (-1 if any(int(s[ax]) < 0 for s in shapes)
                    else sum(int(s[ax]) for s in shapes))
        out.shape = tuple(dims)
    out.lod_level = max(getattr(v, "lod_level", 0) for v in input)
    return out


def sums(input, out=None):
    helper = LayerHelper("sum")
    if out is None:
        out = helper.create_tmp_variable(dtype=input[0].dtype)
    helper.append_op("sum", {"X": [v.name for v in input]},
                     {"Out": [out.name]})
    return out


def assign(input, output=None):
    helper = LayerHelper("assign")
    if output is None:
        output = helper.create_tmp_variable(dtype=input.dtype
                                            if isinstance(input, Variable)
                                            else "float32")
    if isinstance(input, Variable):
        helper.append_op("assign", {"X": [input.name]},
                         {"Out": [output.name]})
    else:
        import numpy as np

        arr = np.asarray(input)
        helper.append_op("assign_value", {}, {"Out": [output.name]},
                         {"shape": list(arr.shape), "dtype": str(arr.dtype),
                          "values": arr.flatten().tolist()})
    return output


def argmax(x, axis=-1):
    helper = LayerHelper("argmax")
    out = helper.create_tmp_variable(dtype="int64", stop_gradient=True)
    helper.append_op("argmax", {"X": [x.name]}, {"Out": [out.name]},
                     {"axis": axis})
    return out


def increment(x, value=1.0, in_place=True):
    helper = LayerHelper("increment")
    out = x if in_place else helper.create_tmp_variable(dtype=x.dtype)
    helper.append_op("increment", {"X": [x.name]}, {"Out": [out.name]},
                     {"step": float(value)})
    return out


def zeros_like(x, out=None):
    helper = LayerHelper("zeros_like")
    if out is None:
        out = helper.create_tmp_variable(dtype=x.dtype)
    helper.append_op("fill_zeros_like", {"X": [x.name]}, {"Out": [out.name]})
    return out
