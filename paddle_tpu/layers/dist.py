"""Sharding-annotation surface for multichip SPMD programs.

The GSPMD discipline (and the reference's evolution target: PAPER.md,
distribute_transpiler + native collectives superseding the pserver
path): users annotate a FEW tensors with per-dim mesh-axis names, a
propagation pass completes the rest, and the compiler/executor inserts
the collectives.  These helpers only record annotations on the Program
IR — they are inert under the serial executor, so one Program trains
serially and on a pod.  Lowering happens in
`DistributeTranspiler.transpile(mode="spmd", mesh=...)`
(parallel/executor.py) via `parallel/spmd.py`; the
`sharding-consistency` analysis pass lints the annotations at build
time (docs/analysis.md).
"""
from __future__ import annotations

from typing import Dict, Optional

from ..core.framework import (Variable, default_main_program,
                              normalize_sharding)

__all__ = ["shard", "set_program_mesh"]


def shard(x, spec, main_program=None):
    """Annotate variable `x` (a Variable or its name) with sharding
    `spec` and return the variable.

    `spec`: one entry per tensor dim — a mesh-axis name, a tuple of
    axis names (dim split over their product), or None (replicated),
    e.g. `shard(h, (None, "tp"))` marks activation `h`'s feature dim
    tensor-split.  Annotating a weight directly
    (`shard("fc_0.w_0", (None, "tp"))`) works too; the spmd
    propagation otherwise derives weight splits from activation
    annotations by the Megatron column/row alternation rule.

    A second annotation on the same var must agree with the first —
    contradictory specs raise here (and are also caught program-wide by
    the sharding-consistency pass for specs that arrive via
    deserialization)."""
    prog = main_program or default_main_program()
    if isinstance(x, Variable):
        v = x
    else:
        v = prog.current_block.var(str(x))
    spec = normalize_sharding(spec)
    if v.sharding is not None and v.sharding != spec:
        raise ValueError(
            f"variable {v.name!r} is already annotated with sharding "
            f"{v.sharding}; refusing the contradictory {spec}")
    v.sharding = spec
    # mirror the annotation on the producing op desc so transpiled /
    # serialized programs carry it op-side as well
    if v.op is not None:
        sh = dict(v.op.dist_attr.get("sharding", {}))
        sh[v.name] = [list(e) if isinstance(e, tuple) else e
                      for e in spec] if spec is not None else None
        v.op.set_dist_attr("sharding", sh)
    # unconditional: params/feeds have no producing op, but the
    # annotation still changes to_dict()/verification results, so
    # version-keyed caches (preflight, fingerprints) must miss
    v.block.program.bump_version()
    return v


def set_program_mesh(axes: Optional[Dict[str, int]], main_program=None):
    """Declare the device-mesh axes ({name: size}) the program's
    sharding annotations refer to.  Optional — the transpiler records
    the mesh it is given — but declaring it up front lets the
    sharding-consistency pass validate axis names and divisibility at
    build time, before any mesh exists."""
    prog = main_program or default_main_program()
    prog.mesh_axes = (None if axes is None
                      else {str(k): int(v) for k, v in axes.items()})
    prog.bump_version()
    return prog.mesh_axes
