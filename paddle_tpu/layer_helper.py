"""LayerHelper: shared parameter/bias/activation plumbing for layers.

Reference: /root/reference/python/paddle/v2/fluid/layer_helper.py:1-397.
Parameters are created in BOTH the main program's global block (as inputs to
compute ops) and the startup program (where their init ops run once).
"""
from __future__ import annotations

from .core.framework import (
    default_main_program,
    default_startup_program,
    unique_name,
)
from .initializer import ConstantInitializer, XavierInitializer

__all__ = ["LayerHelper"]


class LayerHelper:
    def __init__(self, layer_type, **kwargs):
        self.kwargs = kwargs
        self.layer_type = layer_type
        if kwargs.get("name") is None:
            self.name = unique_name(layer_type)
        else:
            self.name = kwargs["name"]

    @property
    def main_program(self):
        return self.kwargs.get("main_program") or default_main_program()

    @property
    def startup_program(self):
        return self.kwargs.get("startup_program") or default_startup_program()

    @property
    def block(self):
        return self.main_program.current_block

    @property
    def param_attr(self):
        return self.kwargs.get("param_attr")

    @property
    def bias_attr(self):
        return self.kwargs.get("bias_attr")

    def input(self, name="input"):
        return self.kwargs[name]

    def multiple_input(self, name="input"):
        v = self.kwargs[name]
        return list(v) if isinstance(v, (list, tuple)) else [v]

    # -- var/param creation --------------------------------------------------
    def create_tmp_variable(self, dtype, stop_gradient=False):
        return self.block.create_var(
            name=unique_name(f"{self.name}.tmp"), dtype=dtype,
            stop_gradient=stop_gradient)

    def create_variable(self, name, **kw):
        return self.block.create_var(name=name, **kw)

    def create_global_variable(self, name=None, persistable=False,
                               dtype="float32", shape=None,
                               stop_gradient=True):
        return self.main_program.global_block().create_var(
            name=name or unique_name(f"{self.name}.global"),
            shape=shape, dtype=dtype, persistable=persistable,
            stop_gradient=stop_gradient)

    def create_parameter(self, attr, shape, dtype, is_bias=False,
                         default_initializer=None, suffix="w"):
        attr = dict(attr or {})
        if attr.get("weight_norm_dim") is not None:
            return self._create_weight_normalize(attr, shape, dtype,
                                                 suffix)
        name = attr.get("name") or unique_name(f"{self.name}.{suffix}")
        init = attr.get("initializer") or default_initializer
        if init is None:
            init = (ConstantInitializer(0.0) if is_bias
                    else XavierInitializer())
        shape = [int(s) for s in shape]
        # amp master weights: layers whose input is already bf16 (an amp
        # intermediate) must still create f32 parameters — bf16 optimizer
        # state is numerically unsound (amp.py design; the bug shows as
        # Adam accumulators exploding on a bf16 bias)
        from .amp import is_bf16_enabled

        if is_bf16_enabled() and str(dtype) == "bfloat16":
            dtype = "float32"
        main_p = self.main_program.global_block().create_parameter(
            name, shape, dtype,
            trainable=attr.get("trainable", True),
            regularizer=attr.get("regularizer"),
            gradient_clip_attr=attr.get("gradient_clip_attr"),
            optimize_attr={"learning_rate": attr.get("learning_rate", 1.0)},
            update_hooks=attr.get("update_hooks"),
            do_model_average=attr.get("do_model_average"),
        )
        # mirror into startup program + emit its init op there
        sb = self.startup_program.global_block()
        sv = sb.create_parameter(name, shape, dtype)
        init(sv, sb)
        return main_p

    def _create_weight_normalize(self, attr, shape, dtype, suffix):
        """w = g * v / ||v|| (reference layer_helper.py:107-304
        _create_weight_normalize, simplified to the norm layouts layers
        use: dim=None -> scalar g; dim=k on <=2-D weights -> g[shape[k]]).
        v and g are the trainable Parameters; the returned w is a Variable
        recomputed by ops in the main program, so gradients flow to v and
        g through the generic VJP."""
        from .initializer import ConstantInitializer

        dim = int(attr.pop("weight_norm_dim"))
        shape = [int(s) for s in shape]
        if dim >= 0 and len(shape) > 2:
            raise NotImplementedError(
                "WeightNormParamAttr dim is supported for <=2-D weights")
        base = attr.pop("name", None) or unique_name(
            f"{self.name}.{suffix}")
        v = self.create_parameter({**attr, "name": base + ".w_v"},
                                  shape, dtype, suffix=suffix)
        g_shape = [1] if dim < 0 else [shape[dim]]
        g = self.create_parameter(
            {**attr, "name": base + ".w_g",
             "initializer": ConstantInitializer(1.0)},
            g_shape, dtype, suffix=suffix)

        reduce_dims = (list(range(len(shape))) if dim < 0 else
                       [d for d in range(len(shape)) if d != dim])

        def norm_ops(block, out_name):
            sq = unique_name(base + ".w_sq")
            ssum = unique_name(base + ".w_ssum")
            for n in (sq, ssum, out_name):
                if not block.has_var(n):
                    block.create_var(name=n, dtype=dtype)
            block.append_op("square", {"X": [v.name]}, {"Out": [sq]}, {})
            block.append_op("reduce_sum", {"X": [sq]}, {"Out": [ssum]},
                            {"dim": reduce_dims, "keep_dim": False,
                             "reduce_all": dim < 0})
            block.append_op("sqrt", {"X": [ssum]}, {"Out": [out_name]},
                            {})

        # startup: g <- ||v_init||  (reference initializes g to the norm)
        sb = self.startup_program.global_block()
        init_norm = unique_name(base + ".w_initnorm")
        norm_ops(sb, init_norm)
        sb.append_op("assign", {"X": [init_norm]}, {"Out": [g.name]}, {})

        # main: w = v * (g / ||v||), broadcast over `dim`
        mb = self.main_program.current_block
        norm_name = unique_name(base + ".w_norm")
        norm_ops(mb, norm_name)
        ratio = mb.create_var(name=unique_name(base + ".w_ratio"),
                              dtype=dtype, shape=g_shape)
        mb.append_op("elementwise_div", {"X": [g.name], "Y": [norm_name]},
                     {"Out": [ratio.name]}, {"axis": -1})
        w = mb.create_var(name=base, dtype=dtype, shape=shape)
        mb.append_op("elementwise_mul", {"X": [v.name], "Y": [ratio.name]},
                     {"Out": [w.name]}, {"axis": max(dim, 0)})
        # tracked per-Program (a class-level list would pin every past
        # program in memory for the life of the process)
        self.main_program.params_with_weight_norm = (
            getattr(self.main_program, "params_with_weight_norm", []))
        self.main_program.params_with_weight_norm.append(w)
        return w

    # -- common layer plumbing ----------------------------------------------
    def append_op(self, *a, **kw):
        return self.block.append_op(*a, **kw)

    # mixed float widths are legal under amp (an embedding path stays
    # f32 while a matmul path emits bf16); params follow the WIDEST
    # float so master weights stay f32.  The promotion set is the
    # amp-relevant trio only — float64 in the mix is a modelling bug
    # (jax runs with x64 disabled by default, so a f64 param would be
    # silently downcast), so it stays a hard error, as do genuinely
    # different kinds (int vs float).
    _FLOAT_WIDTH = {"float32": 3, "bfloat16": 2, "float16": 1}

    def input_dtype(self, name="input"):
        inputs = self.multiple_input(name)
        dtype = None
        for v in inputs:
            if dtype is None or dtype == v.dtype:
                dtype = v.dtype
            elif (str(dtype) in self._FLOAT_WIDTH
                  and str(v.dtype) in self._FLOAT_WIDTH):
                if (self._FLOAT_WIDTH[str(v.dtype)]
                        > self._FLOAT_WIDTH[str(dtype)]):
                    dtype = v.dtype
            else:
                raise ValueError(
                    f"all inputs must have the same dtype, or mix only "
                    f"the amp float widths float16/bfloat16/float32 "
                    f"(got {dtype} and {v.dtype})")
        return dtype

    def append_bias_op(self, input_var, dim_start=1, dim_end=None):
        """+bias over dims [dim_start, dim_end) of the input shape.

        Reference semantics (param_attr.py ParamAttr.to_attr(None) ->
        default ParamAttr): bias_attr=None means a DEFAULT bias is created;
        only bias_attr=False disables it."""
        bias_attr = self.bias_attr
        if bias_attr is False:
            return input_var
        size = list(input_var.shape[dim_start:dim_end])
        if bias_attr is None or bias_attr is True:
            bias_attr = {}
        b = self.create_parameter(bias_attr, shape=size,
                                  dtype=input_var.dtype, is_bias=True,
                                  suffix="b")
        tmp = self.create_tmp_variable(input_var.dtype)
        self.append_op(
            "elementwise_add", {"X": [input_var.name], "Y": [b.name]},
            {"Out": [tmp.name]}, {"axis": dim_start})
        return tmp

    def append_activation(self, input_var):
        act = self.kwargs.get("act")
        if act is None:
            return input_var
        if isinstance(act, str):
            act = {"type": act}
        act = dict(act)
        act_type = act.pop("type")
        tmp = self.create_tmp_variable(input_var.dtype)
        self.append_op(act_type, {"X": [input_var.name]},
                       {"Out": [tmp.name]}, act)
        return tmp
