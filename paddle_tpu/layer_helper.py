"""LayerHelper: shared parameter/bias/activation plumbing for layers.

Reference: /root/reference/python/paddle/v2/fluid/layer_helper.py:1-397.
Parameters are created in BOTH the main program's global block (as inputs to
compute ops) and the startup program (where their init ops run once).
"""
from __future__ import annotations

from .core.framework import (
    default_main_program,
    default_startup_program,
    unique_name,
)
from .initializer import ConstantInitializer, XavierInitializer

__all__ = ["LayerHelper"]


class LayerHelper:
    def __init__(self, layer_type, **kwargs):
        self.kwargs = kwargs
        self.layer_type = layer_type
        if kwargs.get("name") is None:
            self.name = unique_name(layer_type)
        else:
            self.name = kwargs["name"]

    @property
    def main_program(self):
        return self.kwargs.get("main_program") or default_main_program()

    @property
    def startup_program(self):
        return self.kwargs.get("startup_program") or default_startup_program()

    @property
    def block(self):
        return self.main_program.current_block

    @property
    def param_attr(self):
        return self.kwargs.get("param_attr")

    @property
    def bias_attr(self):
        return self.kwargs.get("bias_attr")

    def input(self, name="input"):
        return self.kwargs[name]

    def multiple_input(self, name="input"):
        v = self.kwargs[name]
        return list(v) if isinstance(v, (list, tuple)) else [v]

    # -- var/param creation --------------------------------------------------
    def create_tmp_variable(self, dtype, stop_gradient=False):
        return self.block.create_var(
            name=unique_name(f"{self.name}.tmp"), dtype=dtype,
            stop_gradient=stop_gradient)

    def create_variable(self, name, **kw):
        return self.block.create_var(name=name, **kw)

    def create_global_variable(self, name=None, persistable=False,
                               dtype="float32", shape=None,
                               stop_gradient=True):
        return self.main_program.global_block().create_var(
            name=name or unique_name(f"{self.name}.global"),
            shape=shape, dtype=dtype, persistable=persistable,
            stop_gradient=stop_gradient)

    def create_parameter(self, attr, shape, dtype, is_bias=False,
                         default_initializer=None, suffix="w"):
        attr = dict(attr or {})
        name = attr.get("name") or unique_name(f"{self.name}.{suffix}")
        init = attr.get("initializer") or default_initializer
        if init is None:
            init = (ConstantInitializer(0.0) if is_bias
                    else XavierInitializer())
        shape = [int(s) for s in shape]
        # amp master weights: layers whose input is already bf16 (an amp
        # intermediate) must still create f32 parameters — bf16 optimizer
        # state is numerically unsound (amp.py design; the bug shows as
        # Adam accumulators exploding on a bf16 bias)
        from .amp import is_bf16_enabled

        if is_bf16_enabled() and str(dtype) == "bfloat16":
            dtype = "float32"
        main_p = self.main_program.global_block().create_parameter(
            name, shape, dtype,
            trainable=attr.get("trainable", True),
            regularizer=attr.get("regularizer"),
            gradient_clip_attr=attr.get("gradient_clip_attr"),
            optimize_attr={"learning_rate": attr.get("learning_rate", 1.0)},
            update_hooks=attr.get("update_hooks"),
            do_model_average=attr.get("do_model_average"),
        )
        # mirror into startup program + emit its init op there
        sb = self.startup_program.global_block()
        sv = sb.create_parameter(name, shape, dtype)
        init(sv, sb)
        return main_p

    # -- common layer plumbing ----------------------------------------------
    def append_op(self, *a, **kw):
        return self.block.append_op(*a, **kw)

    def input_dtype(self, name="input"):
        inputs = self.multiple_input(name)
        dtype = None
        for v in inputs:
            if dtype is None:
                dtype = v.dtype
            elif dtype != v.dtype:
                raise ValueError("all inputs must have the same dtype")
        return dtype

    def append_bias_op(self, input_var, dim_start=1, dim_end=None):
        """+bias over dims [dim_start, dim_end) of the input shape.

        Reference semantics (param_attr.py ParamAttr.to_attr(None) ->
        default ParamAttr): bias_attr=None means a DEFAULT bias is created;
        only bias_attr=False disables it."""
        bias_attr = self.bias_attr
        if bias_attr is False:
            return input_var
        size = list(input_var.shape[dim_start:dim_end])
        if bias_attr is None or bias_attr is True:
            bias_attr = {}
        b = self.create_parameter(bias_attr, shape=size,
                                  dtype=input_var.dtype, is_bias=True,
                                  suffix="b")
        tmp = self.create_tmp_variable(input_var.dtype)
        self.append_op(
            "elementwise_add", {"X": [input_var.name], "Y": [b.name]},
            {"Out": [tmp.name]}, {"axis": dim_start})
        return tmp

    def append_activation(self, input_var):
        act = self.kwargs.get("act")
        if act is None:
            return input_var
        if isinstance(act, str):
            act = {"type": act}
        act = dict(act)
        act_type = act.pop("type")
        tmp = self.create_tmp_variable(input_var.dtype)
        self.append_op(act_type, {"X": [input_var.name]},
                       {"Out": [tmp.name]}, act)
        return tmp
