from . import registry  # noqa: F401
from .executor import (  # noqa: F401
    CPUPlace,
    CUDAPlace,
    Executor,
    TPUPlace,
    global_scope,
)
from .framework import (  # noqa: F401
    Block,
    Operator,
    Parameter,
    Program,
    Variable,
    default_main_program,
    default_startup_program,
    program_guard,
)
from .lod import LoDTensor, SelectedRows, TensorArray, create_lod_tensor  # noqa: F401
from .resilience import (  # noqa: F401
    FaultInjector,
    RetryError,
    RetryPolicy,
    fault_injector,
)
from .scope import Scope  # noqa: F401
