"""Build-time shape inference.

The reference implements a per-op `InferShape` twice (compile-time and
runtime contexts, /root/reference/paddle/fluid/framework/shape_inference.h,
operator.cc:330-493).  Here a single default covers most ops: abstractly
evaluate the op's jax lowering with `jax.eval_shape`, substituting a sentinel
size for unknown (-1) dims and mapping it back afterwards.  Ops whose shapes
depend on runtime metadata (LoD, rows) register explicit infer functions via
`registry.register_infer_shape`.

Inference failures are NOT silently swallowed into module state: callers
that care (the analysis package's shape-inference pass, see
paddle_tpu/analysis/passes.py) pass a `report` callback and receive a
structured record per failure / dtype conflict; the build-time hot path
(Block._post_insert) passes nothing and stays cheap.
"""
from __future__ import annotations

import jax

from . import registry
from .execution import ExecContext
from .framework import EMPTY_VAR_NAMES
from .types import np_dtype

# sentinel for unknown dims; any output dim equal to a multiple/exact match is
# mapped back to -1.  Chosen large & prime so arithmetic collisions are rare.
_SENTINEL = 8191


def default_infer_shape(op, block, report=None):
    """Infer output var shapes/dtypes of `op` via jax.eval_shape over its
    lowering.  `report(kind, **details)` (optional) receives:
      * kind="infer-fail",     error=exc          — eval_shape raised;
      * kind="unknown-input",  name=var_name      — an input var has no
        declared shape/dtype yet, so nothing can be inferred;
      * kind="dtype-mismatch", name=..., declared=..., inferred=... —
        the op computes a different dtype than the shared output var
        already declares (two writers disagreeing on one name).
    """
    info = registry.get_op_info(op.type)
    if info.type != op.type:
        return  # generic grad op: grads share forward shapes, handled below
    ins = {}
    for slot, names in op.inputs.items():
        vals = []
        for n in names:
            if n in EMPTY_VAR_NAMES:
                vals.append(None)
                continue
            v = block.var(n)
            if v.shape is None or v.dtype is None:
                if report is not None:
                    report("unknown-input", name=n)
                return
            shape = tuple(_SENTINEL if d < 0 else d for d in v.shape)
            vals.append(jax.ShapeDtypeStruct(shape, np_dtype(v.dtype)))
        ins[slot] = vals
    attrs = {**info.attrs, **op.attrs}
    ctx = ExecContext(jax.random.key(0))
    try:
        outs = jax.eval_shape(lambda i: info.lower(ctx, i, attrs), ins)
    except Exception as e:  # abstract eval of arbitrary lowerings
        if report is not None:
            report("infer-fail", error=e)
        return
    from .types import canonical_dtype

    for slot, names in op.outputs.items():
        vals = outs.get(slot)
        if vals is None:
            continue
        if not isinstance(vals, (list, tuple)):
            vals = [vals]
        for name, aval in zip(names, vals):
            if name in EMPTY_VAR_NAMES or aval is None:
                continue
            leaves = jax.tree_util.tree_leaves(aval)
            if len(leaves) != 1:
                continue
            aval = leaves[0]
            var = block.vars.get(name)
            if var is None:
                continue
            var.shape = tuple(
                -1 if d == _SENTINEL else int(d) for d in aval.shape
            )
            inferred = canonical_dtype(aval.dtype)
            if (report is not None and var.dtype is not None
                    and var.dtype != inferred
                    and var.op is not None and var.op is not op):
                # a DIFFERENT op (the var's recorded producer) already
                # declared another dtype for this shared name; a lone
                # writer re-inferred under changed flags (amp) is not a
                # program bug
                report("dtype-mismatch", name=name, declared=var.dtype,
                       inferred=inferred)
            var.dtype = inferred


def set_output_shape(op, block, slot, shape, dtype=None):
    """Helper for explicit infer fns: declare shape/dtype for every var
    bound to output `slot` (sentinel/undeclared names skipped)."""
    for name in op.output(slot):
        if name in EMPTY_VAR_NAMES:
            continue
        var = block.vars.get(name)
        if var is None:
            continue
        var.shape = tuple(int(d) for d in shape)
        if dtype is not None and var.dtype is None:
            from .types import canonical_dtype

            var.dtype = canonical_dtype(dtype)


def input_var(op, block, slot):
    """First var bound to input `slot`, or None (explicit infer fns use
    this to mirror input shapes; KeyError propagates for dangling names
    so callers see the same contract as default_infer_shape)."""
    names = op.input(slot)
    if not names or names[0] in EMPTY_VAR_NAMES:
        return None
    return block.var(names[0])


def infer_grad_shapes(op, block):
    """'<x>@GRAD' vars mirror their forward var's shape/dtype."""
    from .framework import GRAD_SUFFIX

    for name in op.output_names():
        if name.endswith(GRAD_SUFFIX):
            fwd = name[: -len(GRAD_SUFFIX)]
            var = block.vars.get(name)
            if var is not None and block.has_var(fwd):
                fv = block.var(fwd)
                var.shape = fv.shape
                if var.dtype is None:
                    var.dtype = fv.dtype
