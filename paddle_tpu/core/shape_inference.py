"""Build-time shape inference.

The reference implements a per-op `InferShape` twice (compile-time and
runtime contexts, /root/reference/paddle/fluid/framework/shape_inference.h,
operator.cc:330-493).  Here a single default covers most ops: abstractly
evaluate the op's jax lowering with `jax.eval_shape`, substituting a sentinel
size for unknown (-1) dims and mapping it back afterwards.  Ops whose shapes
depend on runtime metadata (LoD, rows) register explicit infer functions via
`registry.register_infer_shape`.
"""
from __future__ import annotations

import jax

from . import registry
from .execution import ExecContext
from .types import np_dtype

# sentinel for unknown dims; any output dim equal to a multiple/exact match is
# mapped back to -1.  Chosen large & prime so arithmetic collisions are rare.
_SENTINEL = 8191

_failed_ops = set()  # op types whose default inference failed (debug aid)


def default_infer_shape(op, block):
    info = registry.get_op_info(op.type)
    if info.type != op.type:
        return  # generic grad op: grads share forward shapes, handled below
    ins = {}
    for slot, names in op.inputs.items():
        vals = []
        for n in names:
            if n in ("", "@EMPTY@"):
                vals.append(None)
                continue
            v = block.var(n)
            if v.shape is None or v.dtype is None:
                return
            shape = tuple(_SENTINEL if d < 0 else d for d in v.shape)
            vals.append(jax.ShapeDtypeStruct(shape, np_dtype(v.dtype)))
        ins[slot] = vals
    attrs = {**info.attrs, **op.attrs}
    ctx = ExecContext(jax.random.key(0))
    try:
        outs = jax.eval_shape(lambda i: info.lower(ctx, i, attrs), ins)
    except Exception:
        _failed_ops.add(op.type)
        return
    for slot, names in op.outputs.items():
        vals = outs.get(slot)
        if vals is None:
            continue
        if not isinstance(vals, (list, tuple)):
            vals = [vals]
        for name, aval in zip(names, vals):
            if name in ("", "@EMPTY@") or aval is None:
                continue
            leaves = jax.tree_util.tree_leaves(aval)
            if len(leaves) != 1:
                continue
            aval = leaves[0]
            var = block.vars.get(name)
            if var is None:
                continue
            var.shape = tuple(
                -1 if d == _SENTINEL else int(d) for d in aval.shape
            )
            from .types import canonical_dtype

            var.dtype = canonical_dtype(aval.dtype)


def infer_grad_shapes(op, block):
    """'<x>@GRAD' vars mirror their forward var's shape/dtype."""
    from .framework import GRAD_SUFFIX

    for name in op.output_names():
        if name.endswith(GRAD_SUFFIX):
            fwd = name[: -len(GRAD_SUFFIX)]
            var = block.vars.get(name)
            if var is not None and block.has_var(fwd):
                fv = block.var(fwd)
                var.shape = fv.shape
                if var.dtype is None:
                    var.dtype = fv.dtype
