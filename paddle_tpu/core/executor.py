"""Executor: runs a Program's block against a Scope.

Two execution modes over the same op lowerings (core/execution.py):

  * **interpreter** — op-by-op eager execution, the debuggable analogue of the
    reference's `Executor::Run` loop
    (/root/reference/paddle/fluid/framework/executor.cc:80-151), minus its
    known inefficiencies (ops are NOT re-created and re-shape-inferred every
    step; there is no per-step scope rebuild).
  * **compiled** — the whole block is traced into one jax function and
    jit-compiled for XLA; executables are cached keyed by
    (program fingerprint, feed/state shapes+dtypes+LoD, fetch list), which is
    the TPU answer to OpKernel dispatch: one fused executable per
    program+shape bucket instead of per-op kernel launches.

State handling: persistable vars (parameters, optimizer accumulators,
learning-rate vars) live in the root Scope and are threaded through the
compiled function as inputs/outputs; buffers of read-write states are donated
so parameter updates are in-place at the XLA level (the reference gets this
via Param/ParamOut aliasing in optimizer ops, e.g. sgd_op.cc).
"""
from __future__ import annotations

import itertools
import os
import time
import warnings
import weakref
from typing import Dict, List, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from . import flags as flags_mod
from . import registry
from ..observability import metrics as obs_metrics
from ..observability import tracing as obs_tracing
from .execution import DictEnv, ExecContext, ScopeEnv, run_op
from .flags import get_flag
from .framework import Program, Variable, default_main_program
from .lod import LoDTensor
from .scope import Scope


def _dp_replicated_sharding(ops):
    """If any op in `ops` is a parallel_do, a replicated NamedSharding over
    its device mesh (so jitted inputs land on the full device set);
    else None."""
    n = 0
    for op in ops:
        if op.type == "parallel_do":
            n = max(n, int(op.attrs.get("num_places", 1)))
    if n == 0:
        return None
    from ..parallel.mesh import make_mesh, replicated
    return replicated(make_mesh({"dp": min(n, len(jax.devices()))}))


def _run_op_instrumented(ctx, op, env):
    """run_op + optional profiling (reference executor.cc:124 RecordEvent)
    and nan/inf scanning (executor.cc:132-140 FLAGS_check_nan_inf).
    Only eager (interpreter / host-segment) op execution goes through here —
    ops inside a jit trace are compile-time and get no per-op events; compiled
    executions are timed as whole-segment/block events by their callers."""
    from paddle_tpu import profiler

    sync = (lambda: _op_sync(env, op)) if get_flag("benchmark") else None
    if profiler.is_enabled():
        with profiler.record_event(op.type, sync=sync):
            run_op(ctx, op, env)
    else:
        run_op(ctx, op, env)
        if sync is not None:
            sync()
    if get_flag("check_nan_inf"):
        _check_nan_inf(env, op)


def _op_sync(env, op):
    for n in op.output_names():
        v = env.get(n)
        if v is not None:
            jax.tree_util.tree_map(
                lambda x: x.block_until_ready()
                if hasattr(x, "block_until_ready") else x, v)


def _check_nan_inf(env, op):
    for n in op.output_names():
        v = env.get(n)
        if v is None:
            continue
        for leaf in jax.tree_util.tree_leaves(v):
            arr = np.asarray(leaf)
            if np.issubdtype(arr.dtype, np.floating) and \
                    not np.isfinite(arr).all():
                raise RuntimeError(
                    f"Operator {op.type!r} output {n!r} contains "
                    "NaN/Inf (check_nan_inf)")

__all__ = ["CPUPlace", "TPUPlace", "CUDAPlace", "Executor",
           "global_scope", "scope_guard", "switch_scope"]


# ---------------------------------------------------------------------------
# Places (reference platform/place.h:24-53)
# ---------------------------------------------------------------------------


class CPUPlace:
    accelerator = False

    def jax_device(self):
        return jax.devices("cpu")[0]

    def __repr__(self):
        return "CPUPlace"

    def __eq__(self, o):
        return isinstance(o, CPUPlace)


class TPUPlace:
    """Accelerator place; device_id indexes jax.devices()."""

    accelerator = True

    def __init__(self, device_id: int = 0):
        self.device_id = device_id

    def jax_device(self):
        try:
            return jax.devices()[self.device_id]
        except (RuntimeError, IndexError):
            return jax.devices("cpu")[0]

    def __repr__(self):
        return f"TPUPlace({self.device_id})"

    def __eq__(self, o):
        return isinstance(o, TPUPlace) and o.device_id == self.device_id


# API-compat alias: reference models say CUDAPlace; on this stack it is the
# accelerator place.
CUDAPlace = TPUPlace

_global_scope = Scope()


def global_scope() -> Scope:
    return _global_scope


# ---------------------------------------------------------------------------
# helpers
# ---------------------------------------------------------------------------


def _place_feed(v, device):
    """Feed value -> (device value, fresh): `fresh` is True when the
    executor just created the device buffer from host data, i.e. no
    caller-held reference can alias it — the ownership precondition for
    donating the buffer to the jitted step.  A value that arrives as a
    jax array may BE the caller's buffer (device_put to the same device
    is a no-op returning it), so it is never marked fresh."""
    if isinstance(v, LoDTensor):
        return LoDTensor(jax.device_put(np.asarray(v.data), device),
                         v.lod), True
    if isinstance(v, jnp.ndarray):
        # already a jax array: placing it directly avoids a device->host
        # round-trip and keeps a weak dtype weak (np.asarray would do both)
        return jax.device_put(v, device), False
    if isinstance(v, (int, float, bool)) and not isinstance(v, np.generic):
        # same weak-typing rule as _commit below: a Python scalar fed to
        # a bf16 program must not arrive as a strong f32/i64 array
        return jax.device_put(v, device), True
    if isinstance(v, (np.ndarray, jnp.ndarray, np.generic)):
        return jax.device_put(np.asarray(v), device), True
    return v, False  # opaque host object


def _to_device_value(v, device):
    """Feed value -> device arrays (LoDTensor wrapper preserved)."""
    return _place_feed(v, device)[0]


# caps for the liveness-artifact caches: a long-lived executor serving
# many programs (or one whose version keeps bumping — every mutation is
# a fresh fingerprint) must not accumulate plans, and especially not
# full program clones, without bound
_MEMOPT_CACHE_CAP = 16
_PLAN_CACHE_CAP = 256


def _bounded_put(cache: dict, key, value, cap: int):
    """FIFO-evicting insert: dicts iterate in insertion order, so the
    oldest entry goes first once `cap` is reached."""
    while len(cache) >= cap:
        cache.pop(next(iter(cache)))
    cache[key] = value


def _to_numpy(v):
    if isinstance(v, LoDTensor):
        return LoDTensor(np.asarray(v.data), v.lod)
    if isinstance(v, jnp.ndarray):
        return np.asarray(v)
    return v


def _aval_key(v):
    """Hashable (structure, shapes, dtypes) key for one value."""
    leaves, treedef = jax.tree_util.tree_flatten(v)
    return (
        str(treedef),
        tuple((tuple(x.shape), str(x.dtype)) for x in map(jnp.asarray, leaves)),
    )


def _commit(v, target):
    """Commit a state value to `target` (a device or sharding) WITHOUT a
    host round-trip.  jax.jit's internal cache keys on argument
    committed-ness: startup-program outputs are uncommitted (no committed
    inputs), while step outputs of the donated training jit are committed,
    so without normalization the second `exe.run` of an identical config
    re-traces and re-compiles the whole program (measured 384/305/1.5 ms
    on a small MLP; +~60 s via the TPU tunnel).  device_put is a no-op
    returning the same buffer when the value is already committed there."""
    if isinstance(v, LoDTensor):
        return LoDTensor(_commit(v.data, target), v.lod)
    if isinstance(v, jnp.ndarray):
        return jax.device_put(v, target)
    if isinstance(v, (int, float, bool)) and not isinstance(v, np.generic):
        # Python scalars stay weakly typed (device_put direct): routing
        # them through np.asarray would mint a strong f64->f32/i64 array
        # and silently promote bf16 consumers in amp programs
        return jax.device_put(v, target)
    if isinstance(v, (np.ndarray, np.generic)):
        return jax.device_put(np.asarray(v), target)
    return v  # opaque host object


class _MissingState(KeyError):
    pass


_persistent_cache_dir: Optional[str] = None


def _note_cache_config_issue(what: str, exc: Exception) -> None:
    """Persistent-cache config knobs vary across jax versions; a missing
    knob degrades the feature, it must not break execution — but it also
    must not vanish silently (tools/lint.py bans bare swallow-alls)."""
    warnings.warn(
        f"persistent compilation cache: {what} unavailable on this jax "
        f"({type(exc).__name__}: {exc}); continuing without it",
        RuntimeWarning, stacklevel=3)


def _maybe_enable_persistent_cache():
    """Wire JAX's persistent compilation cache when the
    `compilation_cache_dir` flag (env PADDLE_TPU_COMPILATION_CACHE_DIR) is
    set: compiled executables survive process restarts, so a re-launched
    trainer pays deserialization instead of XLA compilation for every
    warm (program, shape) config.  Idempotent; runs on Executor init AND
    on every `set_flags` touching the flag (flags.on_flag_change), so
    enabling/disabling takes effect immediately."""
    global _persistent_cache_dir
    d = get_flag("compilation_cache_dir")
    if d == _persistent_cache_dir or (not d and _persistent_cache_dir
                                      is None):
        return
    if not d:  # flag cleared: actually disable, don't keep the old dir
        jax.config.update("jax_compilation_cache_dir", None)
        try:
            from jax.experimental.compilation_cache import (
                compilation_cache,
            )
            compilation_cache.reset_cache()
        except Exception as e:  # cache module moved/absent in this jax
            _note_cache_config_issue("reset_cache (disable)", e)
        _persistent_cache_dir = None
        return
    jax.config.update("jax_compilation_cache_dir", d)
    for opt, val in (("jax_persistent_cache_min_compile_time_secs", 0.0),
                     ("jax_persistent_cache_min_entry_size_bytes", -1)):
        try:
            jax.config.update(opt, val)
        except Exception as e:
            # option renamed/absent in this jax — dir alone suffices
            _note_cache_config_issue(opt, e)
    try:
        # an earlier compile (e.g. during program build) may have
        # initialized the cache module as disabled; re-point it
        from jax.experimental.compilation_cache import compilation_cache
        compilation_cache.reset_cache()
    except Exception as e:
        _note_cache_config_issue("reset_cache (enable)", e)
    _persistent_cache_dir = d


flags_mod.on_flag_change("compilation_cache_dir",
                         _maybe_enable_persistent_cache)


# ---------------------------------------------------------------------------
# process-wide XLA compile accounting (jax monitoring events)
# ---------------------------------------------------------------------------

# Every backend-compile request in this jax records a
# '/jax/core/compile/backend_compile_duration' event (the duration is
# the XLA compile, or the much cheaper persistent-cache deserialization
# on a hit), and with the persistent cache armed every request
# additionally records a cache_hits/cache_misses event.  Counting them
# gives an exact, backend-level "did anything compile?" signal that the
# serving warm-start contract pins (recompiles_after_warmup == 0 for a
# replica started from a shipped xla_cache artifact) — jit tracing
# alone cannot distinguish a real compile from a cache deserialization.
_xla_compile_counts = {"compiles": 0, "compile_seconds": 0.0,
                       "cache_hits": 0, "cache_misses": 0}
_xla_listeners_installed = False


def _install_xla_event_listeners():
    global _xla_listeners_installed
    if _xla_listeners_installed:
        return
    _xla_listeners_installed = True
    try:
        from jax._src import monitoring as jax_monitoring
    except Exception as e:  # monitoring module moved in this jax
        _note_cache_config_issue("jax._src.monitoring", e)
        return

    def _on_event(name, **kw):
        if name == "/jax/compilation_cache/cache_hits":
            _xla_compile_counts["cache_hits"] += 1
        elif name == "/jax/compilation_cache/cache_misses":
            _xla_compile_counts["cache_misses"] += 1

    def _on_duration(name, secs, **kw):
        if name == "/jax/core/compile/backend_compile_duration":
            _xla_compile_counts["compiles"] += 1
            _xla_compile_counts["compile_seconds"] += float(secs)

    try:
        jax_monitoring.register_event_listener(_on_event)
        jax_monitoring.register_event_duration_secs_listener(_on_duration)
    except Exception as e:
        _note_cache_config_issue("monitoring listener registration", e)


def xla_compile_counts() -> Dict[str, float]:
    """Snapshot of this process's XLA compile activity: `compiles`
    (backend compile requests — each is a real XLA compile or a
    persistent-cache deserialization), `compile_seconds` (wall time
    inside those requests), and `cache_hits`/`cache_misses` (persistent
    compilation cache outcomes; both stay 0 while the cache is
    disabled).  Counters are process-wide and monotonic — take a
    snapshot before an operation and diff after it (what
    GenerationServer's warm-start accounting does)."""
    _install_xla_event_listeners()
    return dict(_xla_compile_counts)


# ---------------------------------------------------------------------------
# Executor
# ---------------------------------------------------------------------------

# cache/compile telemetry lives in the process metrics registry
# (observability.metrics), one label per Executor instance —
# cache_stats() below is a per-instance VIEW over these series.
# always=True: this telemetry predates the PADDLE_TPU_METRICS switch
# (cache_stats must count with metrics off), and lookups happen once
# per run, not per op, so the cost is immaterial.
_EXE_IDS = itertools.count()
_M_LOOKUPS = obs_metrics.counter(
    "paddle_tpu_executor_cache_lookups_total",
    "executable-cache lookups by result (hit/miss)",
    ("exe", "result"), always=True)
_M_COMPILE_S = obs_metrics.counter(
    "paddle_tpu_executor_compile_seconds_total",
    "wall seconds of first invocations (trace + XLA compile + first "
    "dispatch)", ("exe",), always=True)
_M_RECOMPILES = obs_metrics.counter(
    "paddle_tpu_executor_recompiles_after_warmup_total",
    "cache misses for a program that already reached steady state",
    ("exe",), always=True)
_M_ENTRIES = obs_metrics.gauge(
    "paddle_tpu_executor_cache_entries",
    "live executables in the cache", ("exe",), always=True)
_M_RUN_SECONDS = obs_metrics.histogram(
    "paddle_tpu_executor_run_seconds",
    "Executor.run wall latency by execution mode", ("exe", "mode"))


class Executor:
    def __init__(self, place=None, seed: int = 0):
        self.place = place or CPUPlace()
        self._seed = seed
        self._step = 0
        self._cache: Dict = {}
        # weakref-keyed: an id()-keyed map held stale fingerprints past
        # program GC, and a recycled id could serve the WRONG fingerprint
        self._fp_cache: "weakref.WeakKeyDictionary" = \
            weakref.WeakKeyDictionary()  # program -> (version, fp)
        # liveness artifacts, cached per (fingerprint, context): the
        # donation plan feeding donate_argnums, the dead-var free plan
        # the interpreter/segmented paths apply between ops, and the
        # memory-optimized program clones (rename pass)
        self._donation_plans: Dict = {}
        self._free_plans: Dict = {}
        self._memopt_cache: Dict = {}
        self._exe_id = str(next(_EXE_IDS))
        self._m_hits = _M_LOOKUPS.labels(exe=self._exe_id, result="hit")
        self._m_misses = _M_LOOKUPS.labels(exe=self._exe_id,
                                           result="miss")
        self._m_compile_s = _M_COMPILE_S.labels(exe=self._exe_id)
        self._m_recompiles = _M_RECOMPILES.labels(exe=self._exe_id)
        self._m_entries = _M_ENTRIES.labels(exe=self._exe_id)
        self._warm_fps: set = set()
        _maybe_enable_persistent_cache()

    def cache_stats(self) -> Dict:
        """Dispatch/compile telemetry for this Executor's executable cache:
        `hits`/`misses` (cache lookups across compiled + segmented modes),
        `compile_s` (wall time of first invocations, i.e. trace + XLA
        compile + first dispatch), `entries` (live executables), and
        `recompiles_after_warmup` — misses for a program that already had
        a steady-state hit, the signature of a shape/flag leak re-tracing
        the hot path (PADDLE_TPU_LOG_RECOMPILES=1 also warns per event).

        A view over this instance's series in the process metrics
        registry (exported with everything else by
        observability.exporters; see docs/observability.md)."""
        return {"hits": int(self._m_hits.value),
                "misses": int(self._m_misses.value),
                "compile_s": self._m_compile_s.value,
                "recompiles_after_warmup": int(self._m_recompiles.value),
                "entries": len(self._cache)}

    def _note_lookup(self, hit: bool, fp, cache_key, once=None) -> None:
        """`once`: per-run set deduping the recompile counter/warning —
        a segmented run looks up one executable per device segment, but
        one odd-shaped batch is ONE hot-path re-trace, not k."""
        if hit:
            self._m_hits.inc()
            self._warm_fps.add(fp)
            return
        self._m_misses.inc()
        if fp in self._warm_fps and (once is None or fp not in once):
            if once is not None:
                once.add(fp)
            self._m_recompiles.inc()
            if get_flag("log_recompiles"):
                warnings.warn(
                    "Executor recompile after warmup: program fingerprint "
                    f"{fp[:12]}… missed the executable cache with key "
                    f"{cache_key!r} — a feed shape/dtype/LoD or trace-time "
                    "flag changed on the hot path (consider length "
                    "bucketing; see docs/performance.md)",
                    RuntimeWarning, stacklevel=4)

    # -- public API ----------------------------------------------------------
    def run(
        self,
        program: Optional[Program] = None,
        feed: Optional[Dict] = None,
        fetch_list: Optional[Sequence] = None,
        scope: Optional[Scope] = None,
        return_numpy: bool = True,
        compiled: Optional[bool] = None,
    ):
        """Execute block 0 of `program`.  Mirrors reference
        python/paddle/v2/fluid/executor.py:221 (feed/fetch are handled by the
        executor directly instead of injected feed/fetch ops)."""
        program = program or default_main_program()
        scope = scope or global_scope()
        feed = feed or {}
        fetch_names = [
            v.name if isinstance(v, Variable) else str(v)
            for v in (fetch_list or [])
        ]
        # static pre-flight (PADDLE_TPU_VERIFY=warn|error, default off —
        # preflight gates internally): catch bad graphs in ms instead of
        # minutes into a trace; cached per (program, version) so
        # steady-state loops pay one flag read + dict probe
        from ..analysis import preflight

        preflight(program, feed_names=feed.keys(),
                  fetch_names=fetch_names)
        block = program.global_block()

        # jit granularity (flag, docs/performance.md): 'block' = default
        # whole-block executables; 'segment' = the segment cache even for
        # pure-device programs; 'op' = the eager interpreter whose tiny
        # per-op kernels are cached by jax ACROSS programs — the coarse
        # compile-time escape hatch.  An explicit `compiled` arg wins.
        gran = str(get_flag("jit_granularity") or "block").lower()
        if compiled is None:
            if gran == "op":
                compiled = False
            elif not self._has_host_ops(block):
                compiled = True
        step_key = jax.random.fold_in(
            jax.random.key(program.seed or self._seed), self._step
        )
        self._step += 1

        if compiled:
            # host ops can't be jit-traced: "compiled" with host ops
            # means compile the maximal device segments between them
            mode = ("segmented"
                    if self._has_host_ops(block) or gran == "segment"
                    else "compiled")
        elif compiled is None:
            # host ops present (else compiled was defaulted True above):
            # compile maximal device segments, interpret host ops
            # eagerly between them
            mode = "segmented"
        else:
            mode = "interpreted"
        if mode != "compiled" and any(
                getattr(block.vars.get(n), "donate", False) for n in feed):
            # the donate=True build-time guarantee holds on EVERY path:
            # the interpreter/segmented modes cannot fulfill a donation
            # (no jitted step), but an unsafe hint must still fail here
            # — not later, when the same program first hits the
            # compiled path in production
            self._donation_plan(program, feed.keys(), fetch_names, ())
        if get_flag("memory_optimize") and mode != "compiled":
            # liveness rename pass (buffer reuse on the interpreter
            # paths) applied to a cached CLONE keyed by (program, feed,
            # fetch): the caller's program is never mutated, and a later
            # run with a different fetch list gets its own clone with
            # THOSE names protected — fetch values can never be
            # silently clobbered by a rename from an earlier call
            program = self._memopt_program(program, feed.keys(),
                                           fetch_names)
            block = program.global_block()
        t0 = time.perf_counter()
        with obs_tracing.span("executor.run", mode=mode):
            if mode == "segmented":
                outs = self._run_segmented(
                    program, block, scope, feed, fetch_names, step_key
                )
            elif mode == "compiled":
                try:
                    outs = self._run_compiled(
                        program, block, scope, feed, fetch_names, step_key
                    )
                except _MissingState as e:
                    raise RuntimeError(
                        f"persistable variable {e.args[0]!r} has no value "
                        "in scope — run the startup program first"
                    ) from None
            else:
                outs = self._run_interpreted(
                    program, block, scope, feed, fetch_names, step_key
                )
        if obs_metrics.enabled():
            _M_RUN_SECONDS.labels(exe=self._exe_id, mode=mode).observe(
                time.perf_counter() - t0)
        if return_numpy:
            outs = [_to_numpy(v) for v in outs]
        return outs

    def close(self):
        self._cache.clear()
        self._m_entries.set(0)
        # reclaim this instance's registry series (cache_stats() keeps
        # reading the held child objects); processes that churn
        # Executors must not grow every dump without bound
        _M_LOOKUPS.remove(exe=self._exe_id, result="hit")
        _M_LOOKUPS.remove(exe=self._exe_id, result="miss")
        for fam in (_M_COMPILE_S, _M_RECOMPILES, _M_ENTRIES):
            fam.remove(exe=self._exe_id)
        for mode in ("interpreted", "segmented", "compiled"):
            _M_RUN_SECONDS.remove(exe=self._exe_id, mode=mode)

    # -- memory optimization (flag `memory_optimize`) ------------------------
    def _memopt_program(self, program, feed_names, fetch_names):
        """Memory-optimized clone of `program` for one (feed, fetch)
        config, cached: the liveness rename pass runs with the live
        feed/fetch lists auto-skipped, on a deep copy — the user's
        program stays untouched."""
        key = (self._fingerprint(program), tuple(sorted(feed_names)),
               tuple(fetch_names))
        clone = self._memopt_cache.get(key)
        if clone is None:
            from ..memory_optimization_transpiler import memory_optimize

            clone = program.clone()
            memory_optimize(clone,
                            skip_vars=list(feed_names)
                            + list(fetch_names))
            _bounded_put(self._memopt_cache, key, clone,
                         cap=_MEMOPT_CACHE_CAP)
        return clone

    def _free_plan(self, program, fetch_names):
        """Cached {op index -> dead names} for the interpreter/segmented
        paths (memory_optimization_transpiler.plan_dead_frees)."""
        key = (self._fingerprint(program), tuple(fetch_names))
        plan = self._free_plans.get(key)
        if plan is None:
            from ..memory_optimization_transpiler import plan_dead_frees

            plan = plan_dead_frees(program, fetch_names)
            _bounded_put(self._free_plans, key, plan,
                         cap=_PLAN_CACHE_CAP)
        return plan

    def _donation_plan(self, program, feed_names, fetch_names, rw_names):
        """Cached liveness donation plan for one (program, feeds, fetch,
        states) config; raises DonationError for unsafe explicit
        `donate` hints (build time — before any tracing)."""
        key = (self._fingerprint(program), tuple(sorted(feed_names)),
               tuple(fetch_names), tuple(sorted(rw_names)))
        plan = self._donation_plans.get(key)
        if plan is None:
            from ..memory_optimization_transpiler import plan_donation

            block = program.global_block()
            hinted = [n for n in feed_names
                      if n in block.vars
                      and getattr(block.vars[n], "donate", False)]
            plan = plan_donation(program, feed_names, fetch_names,
                                 state_rw_names=rw_names, requested=hinted)
            _bounded_put(self._donation_plans, key, plan,
                         cap=_PLAN_CACHE_CAP)
        return plan.check()

    # -- interpreter ---------------------------------------------------------
    def _has_host_ops(self, block) -> bool:
        return any(self._op_is_host(op) for op in block.ops)

    def _scope_env(self, program, scope, local):
        """ScopeEnv routing persistable writes to the root scope
        (executor.cc:88-117); shared by interpreted and segmented modes."""
        persistable = {v.name for v in program.list_vars() if v.persistable}
        root = scope
        while root.parent is not None:
            root = root.parent

        class _Env(ScopeEnv):
            def get(self, name):
                v = super().get(name)
                if v is None and name in persistable \
                        and name not in self.written:
                    # same diagnosis the compiled path gives via
                    # _MissingState — not a raw op-level AttributeError
                    raise RuntimeError(
                        f"persistable variable {name!r} has no value in "
                        "scope — run the startup program first")
                return v

            def set(self, name, value):
                if name in persistable:
                    root.set_var(name, value)
                else:
                    self.scope.set_var(name, value, local=True)
                self.written.add(name)

        return _Env(local)

    @staticmethod
    def _fetch(env, fetch_names):
        missing = [n for n in fetch_names if not env.has(n)]
        if missing:
            raise KeyError(
                f"fetch variable(s) {missing} were never produced by "
                "the program")
        return [env.get(n) for n in fetch_names]

    def _run_interpreted(self, program, block, scope, feed, fetch_names, key):
        device = self.place.jax_device()
        local = scope.new_scope()
        # dead-var freeing (memory_optimize flag): drop the local-scope
        # reference of every var right after its liveness-proven last
        # use, so footprint tracks LIVE values, not program size
        frees = (self._free_plan(program, fetch_names)
                 if get_flag("memory_optimize") else None)
        try:  # finally: a raising op must not leak the local scope
            env = self._scope_env(program, scope, local)
            with jax.default_device(device):
                for name, v in feed.items():
                    env.set(name, _to_device_value(v, device))
                ctx = ExecContext(key, scope=local, executor=self)
                for i, op in enumerate(block.ops):
                    _run_op_instrumented(ctx, op, env)
                    if frees:
                        for n in frees.get(i, ()):
                            local.erase(n)
                outs = self._fetch(env, fetch_names)
        finally:
            scope.kids.remove(local)
        return outs

    # -- segmented: compiled device segments between eager host ops ---------
    def _op_is_host(self, op) -> bool:
        try:
            info = registry.get_op_info(op.type)
        except KeyError:
            return True
        if info.host:
            return True
        if op.attrs.get("force_cpu"):
            # init_on_cpu(): keep the op out of compiled device programs
            # (its numpy result stays in host memory)
            return True
        sub = op.sub_block() if "sub_block" in op.attrs else None
        return sub is not None and self._has_host_ops(sub)

    def _segments(self, block):
        """Split ops into maximal (is_host, [ops]) runs."""
        segs = []
        for op in block.ops:
            h = self._op_is_host(op)
            if segs and segs[-1][0] == h:
                segs[-1][1].append(op)
            else:
                segs.append((h, [op]))
        return segs

    def _run_segmented(self, program, block, scope, feed, fetch_names, key):
        """Interpreter-shaped env, but each maximal run of non-host ops is
        traced+jitted once and cached — host ops (save/load/print/metrics)
        run eagerly between compiled segments.  The per-op PRNG keys are
        derived from op identity (execution.py:_op_rng_tag), so randomness
        is identical across interpreted/compiled/segmented modes."""
        device = self.place.jax_device()
        local = scope.new_scope()
        # dead-var freeing at segment granularity (memory_optimize flag):
        # names whose last use falls inside a segment are dropped from
        # the local scope once that segment completes
        frees = (self._free_plan(program, fetch_names)
                 if get_flag("memory_optimize") else None)
        try:  # finally: a raising op must not leak the local scope
            env = self._scope_env(program, scope, local)
            fp = self._fingerprint(program)
            with jax.default_device(device):
                for name, v in feed.items():
                    env.set(name, _to_device_value(v, device))
                ctx = ExecContext(key, scope=local, executor=self)
                once = set()  # one recompile count per run, not per seg
                op_idx = 0
                for seg_idx, (is_host, ops) in enumerate(
                        self._segments(block)):
                    if is_host:
                        for op in ops:
                            _run_op_instrumented(ctx, op, env)
                    else:
                        self._run_segment_compiled(fp, seg_idx, ops, env,
                                                   key, device, once)
                    if frees:
                        for i in range(op_idx, op_idx + len(ops)):
                            for n in frees.get(i, ()):
                                local.erase(n)
                    op_idx += len(ops)
                outs = self._fetch(env, fetch_names)
        finally:
            scope.kids.remove(local)
        return outs

    def _run_segment_compiled(self, fp, seg_idx, ops, env, key, device,
                              once=None):
        # names this segment reads from the surrounding env
        read, written = [], set()
        for op in ops:
            for n in op.input_names():
                if n not in written and n not in read and env.has(n):
                    read.append(n)
            written.update(op.output_names())
        repl = _dp_replicated_sharding(ops)
        in_vals = {n: _commit(env.get(n), repl if repl is not None else device)
                   for n in read}
        cache_key = (
            fp, "seg", seg_idx,
            tuple((n, _aval_key(v)) for n, v in sorted(in_vals.items())),
            get_flag("amp_bf16"),  # amp changes traced compute dtypes
            get_flag("conv_layout"),  # changes the traced conv layout
            get_flag("flash_min_seq_k"),  # changes the traced attn path
            get_flag("flash_pack_heads"),  # changes the traced kernel
            get_flag("flash_block_q"), get_flag("flash_block_k"),
        )
        fn = self._cache.get(cache_key)
        miss = fn is None
        self._note_lookup(not miss, fp, cache_key, once)
        if miss:
            def fn(vals, rng_key, _ops=tuple(ops)):
                seg_env = DictEnv(vals)
                seg_ctx = ExecContext(rng_key, executor=self, compiled=True)
                for op in _ops:
                    run_op(seg_ctx, op, seg_env)
                return {n: seg_env.d[n] for n in seg_env.written
                        if n in seg_env.d}
            if repl is not None:
                fn = jax.jit(fn, in_shardings=(repl, repl))
            else:
                fn = jax.jit(fn)
            self._cache[cache_key] = fn
        from paddle_tpu import profiler

        t0 = time.perf_counter() if miss else None
        if profiler.is_enabled():
            with profiler.record_event(f"xla_segment_{seg_idx}"):
                out = fn(in_vals, key)
                jax.block_until_ready(out)
        else:
            out = fn(in_vals, key)
        if miss:
            self._m_compile_s.inc(time.perf_counter() - t0)
            self._m_entries.set(len(self._cache))
        for n, v in out.items():
            env.set(n, v)

    # -- compiled ------------------------------------------------------------
    def _fingerprint(self, program) -> str:
        ent = self._fp_cache.get(program)
        if ent is not None and ent[0] == program._version:
            return ent[1]
        fp = program.fingerprint()
        self._fp_cache[program] = (program._version, fp)
        return fp

    @staticmethod
    def _analyze_states(program, block, feed_names):
        """Persistable vars read (before being written) and written by ops."""
        persistable = {v.name for v in program.list_vars() if v.persistable}

        def visit(blk, written, reads, writes):
            for op in blk.ops:
                for n in op.input_names():
                    if n in persistable and n not in written:
                        reads.add(n)
                sub = op.sub_block() if "sub_block" in op.attrs else None
                if sub is not None:
                    visit(sub, written, reads, writes)
                for n in op.output_names():
                    if n in persistable:
                        writes.add(n)
                        written.add(n)

        reads, writes = set(), set()
        visit(block, set(feed_names), reads, writes)
        return sorted(reads), sorted(writes)

    def _run_compiled(self, program, block, scope, feed, fetch_names, key):
        device = self.place.jax_device()
        feed_vals, fresh = {}, set()
        for n, v in feed.items():
            feed_vals[n], is_fresh = _place_feed(v, device)
            if is_fresh:
                fresh.add(n)
        state_in_names, state_out_names = self._analyze_states(
            program, block, feed_vals.keys()
        )
        ro_names = [n for n in state_in_names if n not in state_out_names]
        rw_names = [n for n in state_in_names if n in state_out_names]

        # liveness donation plan (memory_optimization_transpiler): which
        # buffers die inside this step.  Read-write states are always
        # donated (the in-place param update); feed buffers are donated
        # under the memory_optimize flag or an explicit per-var `donate`
        # hint — but only when the executor itself created the device
        # buffer (`fresh`), so a caller-held array is never invalidated.
        # Unsafe explicit hints raise DonationError here, at build time.
        plan = self._donation_plan(program, feed_vals.keys(), fetch_names,
                                   rw_names)
        donate_all_feeds = get_flag("memory_optimize")
        hinted = {n for n in plan.feeds
                  if n in block.vars
                  and getattr(block.vars[n], "donate", False)}
        don_names = tuple(sorted(
            n for n in (plan.feeds if donate_all_feeds else hinted)
            if n in fresh))

        def get_state(n):
            if not scope.has_var(n) or scope.find_var(n) is None:
                raise _MissingState(n)
            return scope.find_var(n)

        repl = _dp_replicated_sharding(block.ops)
        target = repl if repl is not None else device
        ro = {n: _commit(get_state(n), target) for n in ro_names}
        rw = {n: _commit(get_state(n), target) for n in rw_names}

        cache_key = (
            self._fingerprint(program),
            block.idx,
            tuple(sorted((n, _aval_key(v)) for n, v in feed_vals.items())),
            tuple((n, _aval_key(v)) for n, v in ro.items()),
            tuple((n, _aval_key(v)) for n, v in rw.items()),
            tuple(fetch_names),
            str(device),
            don_names,  # donation is baked into the executable
            get_flag("amp_bf16"),  # amp changes traced compute dtypes
            get_flag("conv_layout"),  # changes the traced conv layout
            get_flag("flash_min_seq_k"),  # changes the traced attn path
            get_flag("flash_pack_heads"),  # changes the traced kernel
            get_flag("flash_block_q"), get_flag("flash_block_k"),
        )
        fn = self._cache.get(cache_key)
        miss = fn is None
        self._note_lookup(not miss, cache_key[0], cache_key)
        if miss:
            fn = self._build_compiled_fn(
                block, fetch_names, state_out_names, repl
            )
            self._cache[cache_key] = fn
        don_feeds = {n: feed_vals[n] for n in don_names}
        keep_feeds = {n: v for n, v in feed_vals.items()
                      if n not in don_feeds}
        from paddle_tpu import profiler

        t0 = time.perf_counter() if miss else None
        if profiler.is_enabled():
            with profiler.record_event("xla_block"):
                fetches, state_out = fn(don_feeds, keep_feeds, ro, rw, key)
                jax.block_until_ready((fetches, state_out))
        else:
            fetches, state_out = fn(don_feeds, keep_feeds, ro, rw, key)
        if miss:
            self._m_compile_s.inc(time.perf_counter() - t0)
            self._m_entries.set(len(self._cache))
        for n, v in state_out.items():
            scope.set_var(n, v)
        return [fetches[n] for n in fetch_names]

    def _build_compiled_fn(self, block, fetch_names, state_out_names,
                           repl=None):
        def fn(don_feeds, keep_feeds, ro, rw, rng_key):
            env = DictEnv({**ro, **rw, **keep_feeds, **don_feeds})
            ctx = ExecContext(rng_key, executor=self, compiled=True)
            for op in block.ops:
                run_op(ctx, op, env)
            fetches = {n: env.get(n) for n in fetch_names}
            state_out = {
                n: env.d[n]
                for n in state_out_names
                if n in env.written and n in env.d
            }
            return fetches, state_out

        # donation plan (core/executor._run_compiled): arg 0 carries the
        # liveness-dead feed buffers, arg 3 the read-write states whose
        # old values die with the in-place update — XLA reuses both HBM
        # regions for intermediates/outputs
        if repl is not None:
            # a parallel_do op constrains values to a multi-device mesh:
            # land every input replicated on that device set so the
            # partitioner may shard the annotated subgraph (single-device
            # committed args would conflict with the mesh)
            return jax.jit(fn, donate_argnums=(0, 3),
                           in_shardings=(repl, repl, repl, repl, repl))
        return jax.jit(fn, donate_argnums=(0, 3))


def program_to_fn(program: Program, feed_names, fetch_names, block_idx=0):
    """Expose a Program block as a pure jax function
    `(feeds, states, rng_key) -> (fetches, new_states)` for direct use with
    jax transforms (jit/pjit/shard_map) — the bridge used by
    __graft_entry__ and the parallel package."""
    block = program.blocks[block_idx]
    state_in, state_out = Executor._analyze_states(program, block, feed_names)

    def fn(feeds, states, rng_key):
        env = DictEnv({**states, **feeds})
        ctx = ExecContext(rng_key, compiled=True)
        for op in block.ops:
            run_op(ctx, op, env)
        fetches = {n: env.get(n) for n in fetch_names}
        # pass read-only states through so callers can loop
        # `states = fn(...)[1]` without re-merging
        new_states = {
            n: env.d[n]
            for n in sorted(set(state_in) | set(state_out))
            if n in env.d
        }
        return fetches, new_states

    fn.state_in_names = state_in
    fn.state_out_names = state_out
    # liveness donation plan for callers that jit this fn themselves
    # (benchmark/harness.py, parallel.ParallelExecutor): which feed
    # buffers die inside the step, and therefore may ride donate_argnums
    from ..memory_optimization_transpiler import plan_donation

    rw = [n for n in state_in if n in state_out]
    fn.donation_plan = plan_donation(program, feed_names, fetch_names,
                                     state_rw_names=rw).check()
    return fn


def switch_scope(scope: Scope) -> Scope:
    """Replace the global scope, returning the previous one (reference
    executor.py switch_scope / pybind _switch_scope)."""
    global _global_scope
    prev = _global_scope
    _global_scope = scope
    return prev


class scope_guard:
    """`with fluid.scope_guard(scope): ...` — run with a different global
    scope (reference executor.py scope_guard)."""

    def __init__(self, scope: Scope):
        self._scope = scope
        self._prev = None

    def __enter__(self):
        self._prev = switch_scope(self._scope)
        return self._scope

    def __exit__(self, *exc):
        switch_scope(self._prev)
        return False
