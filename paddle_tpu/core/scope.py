"""Scope: hierarchical name -> runtime value map.

Reference: /root/reference/paddle/fluid/framework/scope.h:38-81.  Values are
jax Arrays (dense tensors), LoDTensor / SelectedRows / TensorArray wrappers
(core/lod.py), or opaque python objects (readers, rank tables).
"""
from __future__ import annotations

from typing import Dict, Optional


class Scope:
    def __init__(self, parent: Optional["Scope"] = None):
        self.parent = parent
        self._vars: Dict[str, object] = {}
        self.kids = []

    def var(self, name: str):
        """Get-or-create (returns None placeholder if new)."""
        if name not in self._vars:
            s = self._find_scope(name)
            if s is not None:
                return s._vars[name]
            self._vars[name] = None
        return self._vars[name]

    def new_scope(self) -> "Scope":
        s = Scope(self)
        self.kids.append(s)
        return s

    def drop_kids(self):
        self.kids.clear()

    def _find_scope(self, name) -> Optional["Scope"]:
        s = self
        while s is not None:
            if name in s._vars:
                return s
            s = s.parent
        return None

    def find_var(self, name: str):
        s = self._find_scope(name)
        if s is None:
            raise KeyError(f"variable '{name}' not found in scope")
        return s._vars[name]

    def has_var(self, name: str) -> bool:
        return self._find_scope(name) is not None

    def set_var(self, name: str, value, local: bool = False):
        """Write `value`.  Non-local writes update the owning scope if the
        name already exists somewhere up the chain (matches executor
        semantics where persistables live in the global scope)."""
        if not local:
            s = self._find_scope(name)
            if s is not None:
                s._vars[name] = value
                return
        self._vars[name] = value

    def local_names(self):
        return list(self._vars.keys())

    def erase(self, name: str):
        self._vars.pop(name, None)
