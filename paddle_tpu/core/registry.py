"""Operator registry: the TPU-native answer to the reference's OpRegistry.

Reference: /root/reference/paddle/fluid/framework/op_registry.h:127-241
(`REGISTER_OP*` macros) and op_info.h:34 (`OpInfo{grad_op_maker_, infer_shape_}`).

Instead of per-(place,dtype,layout,library) kernel pairs dispatched at runtime
(operator.cc:494 RunImpl), every op registers ONE `lower` function expressed in
jax.numpy / lax.  That single definition serves as:
  * the CPU interpreter kernel (eager execution, debuggable), and
  * the XLA lowering used when a whole Block is traced and jit-compiled
    (core/compiler.py) — kernel fusion, tiling and layout are left to XLA,
    which is the TPU replacement for the hand-written CUDA kernel corpus.

Gradients: ops may register an explicit `grad_maker` (emitting grad-op descs
like the reference's GradOpMaker), but the default is a *generic VJP grad*:
a `<type>_grad` op whose lowering calls `jax.vjp` on the forward lowering.
XLA CSE dedupes the re-traced forward, so this costs nothing after fusion and
guarantees analytic gradients exactly consistent with the forward op.
"""
from __future__ import annotations

import dataclasses
from typing import Callable, Dict, List, Optional, Sequence


@dataclasses.dataclass
class OpInfo:
    type: str
    # lower(ctx, ins, attrs) -> {output_slot: [values]}
    lower: Callable = None
    # infer_shape(op, block) -> None ; fills output VarDesc shapes at build time
    infer_shape: Callable = None
    # grad_maker(op, block, no_grad_set) -> list[OpSpec dicts] ; None = generic
    grad_maker: Callable = None
    # input slots that are differentiable (None = all float inputs)
    diff_inputs: Optional[Sequence[str]] = None
    # output slots that are differentiable (None = all)
    diff_outputs: Optional[Sequence[str]] = None
    # declared slot names (for validation / layer autogen); duplicable slots
    # accept a list of vars
    inputs: Sequence[str] = ()
    outputs: Sequence[str] = ()
    # slots that legitimately take MORE THAN ONE var (sum's X, concat's X,
    # split's Out...) — the reference marks these per-slot with
    # AsDuplicable() in the OpMaker (framework.proto OpProto::Var.duplicable);
    # the analysis arity pass flags multi-name bindings to any other slot
    dup_inputs: Sequence[str] = ()
    dup_outputs: Sequence[str] = ()
    # attr defaults
    attrs: Dict = dataclasses.field(default_factory=dict)
    # in-place aliases {output_slot: input_slot} (optimizer ops: ParamOut<-Param)
    inplace: Dict[str, str] = dataclasses.field(default_factory=dict)
    # True if op is stateful/random (needs a PRNG key via ctx)
    random: bool = False
    # True -> never differentiate through (metrics, optimizer ops)
    not_differentiable: bool = False
    # True -> must run on host (save/load, print, readers); forces the
    # executor to interpret rather than trace the enclosing block segment
    host: bool = False
    # static cost metadata (analysis/cost_model.py): `cost_kind` names the
    # estimator class ("matmul", "conv", "attention", "moe", "embedding",
    # "elementwise", "reduction", "norm", "data", "collective", "free");
    # `cost_fn(op, resolve)` (register_op_cost) overrides the class with an
    # exact per-op estimator.  Ops with neither report as cost-UNKNOWN —
    # the analyzer surfaces them instead of silently counting zero.
    cost_kind: Optional[str] = None
    cost_fn: Callable = None


_REGISTRY: Dict[str, OpInfo] = {}


def register_op(
    type: str,
    inputs: Sequence[str] = (),
    outputs: Sequence[str] = (),
    attrs: Dict = None,
    diff_inputs: Optional[Sequence[str]] = None,
    diff_outputs: Optional[Sequence[str]] = None,
    inplace: Dict[str, str] = None,
    random: bool = False,
    not_differentiable: bool = False,
    host: bool = False,
    dup_inputs: Sequence[str] = (),
    dup_outputs: Sequence[str] = (),
    cost: Optional[str] = None,
):
    """Decorator: register `fn` as the lowering for op `type`."""

    def deco(fn):
        info = _REGISTRY.get(type) or OpInfo(type=type)
        info.lower = fn
        if cost is not None:
            info.cost_kind = cost
        info.inputs = tuple(inputs)
        info.outputs = tuple(outputs)
        info.dup_inputs = tuple(dup_inputs)
        info.dup_outputs = tuple(dup_outputs)
        info.attrs = dict(attrs or {})
        info.diff_inputs = diff_inputs
        info.diff_outputs = diff_outputs
        info.inplace = dict(inplace or {})
        info.random = random
        info.not_differentiable = not_differentiable
        info.host = host
        _REGISTRY[type] = info
        return fn

    return deco


def register_infer_shape(type: str):
    def deco(fn):
        info = _REGISTRY.setdefault(type, OpInfo(type=type))
        info.infer_shape = fn
        return fn

    return deco


def register_op_cost(type: str, kind: Optional[str] = None):
    """Attach static cost metadata to op `type`'s OpInfo.

    Used as a decorator, registers an exact estimator
    `fn(op, resolve) -> analysis.cost_model.OpCost` (`resolve(name)`
    returns the `(shape, dtype)` of a var with -1 dims already
    substituted).  Called bare — `register_op_cost("relu",
    kind="elementwise")` — it records just the estimator class.  Either
    form may target an op registered elsewhere (the analysis layer
    annotates the existing corpus without touching every lowering)."""
    info = _REGISTRY.setdefault(type, OpInfo(type=type))
    if kind is not None:
        info.cost_kind = kind

    def deco(fn):
        info.cost_fn = fn
        return fn

    return deco


def set_op_cost_kind(type: str, kind: str, overwrite: bool = False):
    """Record the estimator class for `type` (no-op for unregistered ops
    — cost metadata must never invent op types)."""
    info = _REGISTRY.get(type)
    if info is not None and (overwrite or info.cost_kind is None):
        info.cost_kind = kind


def register_grad_maker(type: str):
    def deco(fn):
        info = _REGISTRY.setdefault(type, OpInfo(type=type))
        info.grad_maker = fn
        return fn

    return deco


def get_op_info(type: str) -> OpInfo:
    info = _REGISTRY.get(type)
    if info is None or info.lower is None:
        # grad ops resolve generically: "<fwd>_grad" with no explicit lowering
        if type.endswith("_grad") and type[: -len("_grad")] in _REGISTRY:
            return _REGISTRY[type[: -len("_grad")]]
        raise KeyError(f"op '{type}' is not registered")
    return info


def has_op(type: str) -> bool:
    try:
        get_op_info(type)
        return True
    except KeyError:
        return False


def registered_ops() -> List[str]:
    return sorted(t for t, i in _REGISTRY.items() if i.lower is not None)
