"""Runtime flag registry — the gflags analogue.

Reference: the reference scatters `DEFINE_bool/int32/double` through the
C++ (utils/Flags.cpp:18-85 legacy; executor.cc:29-32 FLAGS_benchmark /
FLAGS_check_nan_inf) and plumbs python argv via `core.init_gflags`
(framework/init.cc).  Here flags are a simple process-global registry,
settable from code (`set_flags`) or `PADDLE_TPU_<NAME>` environment
variables at import.
"""
from __future__ import annotations

import os
from typing import Any, Dict

_DEFS: Dict[str, Any] = {}
_VALUES: Dict[str, Any] = {}
_ON_CHANGE: Dict[str, list] = {}


def on_flag_change(name: str, callback):
    """Register `callback()` to run whenever `set_flags` touches `name` —
    for flags that must take effect immediately rather than at the next
    consumer read (e.g. compilation_cache_dir re-pointing JAX's
    persistent cache)."""
    _ON_CHANGE.setdefault(name, []).append(callback)


def define_flag(name: str, default, help_str: str = ""):
    _DEFS[name] = (default, help_str)
    env = os.environ.get("PADDLE_TPU_" + name.upper())
    if env is not None:
        if isinstance(default, bool):
            _VALUES[name] = env.lower() in ("1", "true", "yes", "on")
        elif isinstance(default, int):
            _VALUES[name] = int(env)
        elif isinstance(default, float):
            _VALUES[name] = float(env)
        else:
            _VALUES[name] = env
    else:
        _VALUES[name] = default


def get_flag(name: str):
    return _VALUES[name]


def set_flags(flags: Dict[str, Any]):
    for k, v in flags.items():
        if k not in _DEFS:
            raise KeyError(f"unknown flag {k!r}; defined: {sorted(_DEFS)}")
        _VALUES[k] = v
    for k in flags:
        for cb in _ON_CHANGE.get(k, ()):
            cb()


def flag_defaults():
    return {k: d for k, (d, _) in _DEFS.items()}


# -- the reference's executor/debug flags -----------------------------------
define_flag("check_nan_inf", False,
            "scan every op output for nan/inf in interpreter mode "
            "(executor.cc FLAGS_check_nan_inf)")
define_flag("benchmark", False,
            "per-op sync + timing logs (executor.cc FLAGS_benchmark)")
define_flag("amp_bf16", False,
            "mixed precision: whitelisted MXU ops (mul/matmul/conv) cast "
            "float32 operands to bfloat16; optimizer ops keep float32 "
            "master params (dtype promotion upcasts bf16 grads)")
define_flag("flash_min_seq_k", -1,
            "override the flash-attention Pallas/XLA crossover for ops "
            "that did not set min_seq_k explicitly: -1 = kernel policy "
            "default (~2k), 0 = always use the Pallas kernel.  Below the "
            "crossover the XLA composition is faster for ISOLATED "
            "attention, but in a full training step it materializes "
            "scores+probs (f32 after the softmax upcast) for backward — "
            "at large d_model that dominates HBM traffic and memory, so "
            "training benches force the kernel (run_ridge.py).  Read at "
            "TRACE time: Executor caches key on it like amp_bf16")
define_flag("flash_block_q", -1,
            "override the flash kernel's shape-keyed Q block size "
            "(-1 = the measured table in kernels/flash_attention."
            "_select_blocks); tuning/benchmark hook, read at TRACE time")
define_flag("flash_block_k", -1,
            "override the flash kernel's shape-keyed K block size "
            "(-1 = the measured table); tuning/benchmark hook, read at "
            "TRACE time")
define_flag("log_recompiles", False,
            "warn (RuntimeWarning) whenever the Executor misses its "
            "executable cache for a program that already reached "
            "steady-state (had a cache hit) — the signature of a feed "
            "shape/dtype/LoD or trace-time-flag leak re-tracing the hot "
            "path.  Counted unconditionally in Executor.cache_stats()"
            "['recompiles_after_warmup']")
define_flag("compilation_cache_dir", "",
            "directory for JAX's persistent compilation cache: compiled "
            "executables survive process restarts, so a relaunched "
            "trainer pays deserialization instead of XLA compile time "
            "for warm configs.  Wired on Executor init "
            "(core/executor.py:_maybe_enable_persistent_cache)")
define_flag("verify", "off",
            "static program verification before execution "
            "(paddle_tpu.analysis): 'off' = skip; 'warn' = run every "
            "registered analysis pass and RuntimeWarning on "
            "error/warning diagnostics; 'error' = additionally raise "
            "ProgramVerificationError on error-severity diagnostics.  "
            "Applies to Executor, ParallelExecutor, PipelineExecutor "
            "and io.load_inference_model; results are cached per "
            "(program, version) so steady-state loops verify once.  "
            "Explicit Program.verify(level=...) calls ignore this flag")
define_flag("prefetch_depth", 0,
            "default Trainer.train prefetch depth: N > 0 runs reader + "
            "DataFeeder.feed + device_put N batches ahead on a "
            "background thread (reader/pipeline.py); 0 keeps the serial "
            "loop.  Per-call override: Trainer.train(prefetch=N)")
define_flag("sync_every_n", 1,
            "default Trainer.train fetch-sync cadence: K > 1 hands "
            "EndIteration a LazyFetch cost (device->host copy deferred "
            "until read) and fences the dispatch queue every K steps; "
            "1 materializes every step (the serial loop).  Per-call "
            "override: Trainer.train(sync_every_n=K)")
define_flag("metrics", False,
            "arm the observability metrics instruments "
            "(paddle_tpu.observability.metrics): counters/gauges/"
            "histograms over the executor, trainer, reader pipeline, "
            "serving, pserver transport and resilience hot paths.  Off "
            "(default): every instrument is a boolean-test no-op; "
            "telemetry-API metrics (Executor.cache_stats, "
            "InferenceServer.stats) count regardless.  Export via "
            "observability.exporters (Prometheus text / HTTP / JSON) "
            "or PADDLE_TPU_METRICS_DUMP=<path> at exit")
define_flag("trace_dir", "",
            "directory for Chrome-trace JSON dumps "
            "(paddle_tpu.observability.tracing): setting it enables "
            "span recording (trace/span/parent ids, propagated over "
            "the pserver wire protocol and to worker threads) and "
            "auto-writes trace_<pid>.json at process exit — open in "
            "chrome://tracing or Perfetto (docs/observability.md)")
define_flag("comm_bucket_bytes", 4 << 20,
            "size cap (bytes) for fused pserver transfers: send ops "
            "pack grads into arrival-order buckets (DDP-style) and "
            "ship each bucket as ONE SEND_BATCH frame "
            "(parallel/comm.py + parallel/pserver.py).  0 disables "
            "fusion — every var goes in its own legacy SEND frame "
            "(the pre-bucketing wire path; also the automatic "
            "fallback against a server that predates the batch "
            "verbs).  An oversized var still ships, alone in its "
            "bucket")
define_flag("overlap_bucket_bytes", 4 << 20,
            "size cap (bytes) for the compute/collective-overlap "
            "gradient buckets of the spmd path (docs/performance.md "
            "'Multichip sharding'): ParallelExecutor(overlap="
            "'bucketed'|'auto') concatenates parameter gradients in "
            "production (backward) order into buckets of at most this "
            "many bytes and issues ONE lax.psum per bucket, so early "
            "buckets' all-reduces overlap with the remaining backward "
            "compute (DDP-style).  0 puts every gradient in its own "
            "bucket; the bucket count is pinned structurally via "
            "compiled_collectives")
define_flag("memory_optimize", False,
            "whole-program memory optimization "
            "(memory_optimization_transpiler + docs/performance.md "
            "'Memory'): the Executor derives a liveness-backed donation "
            "plan and donates every feed buffer whose last use is "
            "inside the jitted step (read-write state donation is "
            "always on), frees dead local-scope vars between ops/"
            "segments on the interpreter paths, and applies the "
            "liveness rename pass (buffer reuse) to interpreted/"
            "segmented programs, auto-skipping the current feed and "
            "fetch lists.  The rename runs on a cached clone — the "
            "caller's Program is never mutated — and re-keys per-op "
            "PRNG streams of renamed temporaries: same distribution, "
            "different draws than the unrenamed program")
define_flag("remat", False,
            "default rematerialization for model builders that accept "
            "remat=None (models.resnet, models.transformer): wrap each "
            "residual/attention block in layers.recompute "
            "(jax.checkpoint) so block-internal activations re-run in "
            "backward instead of living in HBM — the bytes-for-FLOPs "
            "trade of Chen et al. (sublinear memory cost).  Read at "
            "BUILD time (program construction), not trace time")
define_flag("conv_layout", "",
            "opt-in conv layout override, read at TRACE time: 'NHWC' "
            "runs every NCHW-declared conv2d channels-last inside the "
            "lowering (transpose in, NHWC conv, transpose out — XLA "
            "cancels adjacent pairs between consecutive convs), the "
            "TPU's native vector-lane layout.  '' (default) keeps each "
            "op's declared data_format.  Executor cache keys include "
            "it like amp_bf16; combine with amp_bf16 for the "
            "bf16-native NHWC path")
define_flag("jit_granularity", "block",
            "how much program one executable covers: 'block' (default) "
            "traces whole block 0 into one XLA program; 'segment' "
            "compiles maximal device segments (the mode host ops "
            "already force) even for pure-device programs; 'op' runs "
            "the eager interpreter — each jax op compiles tiny "
            "kernels cached ACROSS programs, the coarse-compile "
            "escape hatch when whole-program XLA compile time "
            "dominates short runs (docs/performance.md).  An explicit "
            "Executor.run(compiled=...) argument overrides it")
define_flag("serving_kv_dtype", "",
            "default KV-pool storage precision for "
            "models.transformer.build_lm_paged_decoder when the caller "
            "passes kv_dtype=None (docs/serving.md 'KV quantization'): "
            "'' or 'fp32' = float32 blocks; 'bf16' = bfloat16 blocks "
            "(half the resident KV bytes); 'int8' = int8 blocks with "
            "one float32 scale per (layer, block), quantize-on-write / "
            "dequantize-on-gather (~4x fewer resident KV bytes, so the "
            "same HBM budget holds ~2x the sequences K+V vs bf16 and "
            "~4x vs fp32).  Read at BUILD time; the model-dir spec's "
            "kv_dtype and explicit builder/server args override it")
define_flag("serving_kernels", "auto",
            "Pallas serving-kernel tier selection "
            "(docs/performance.md 'Serving kernels'): 'auto' (default) "
            "arms the paged-attention decode / fused MoE dispatch / "
            "fused bucket-update kernels on TPU backends only; 'on' "
            "arms everywhere (non-TPU backends run them under Pallas "
            "interpret mode — a correctness harness, not a fast "
            "path); 'off' keeps the XLA oracle path.  Env alias "
            "PADDLE_TPU_SERVING_KERNELS.  Armed-but-unsupported "
            "shape/dtype/platform combinations fall back to the "
            "oracle per op, silently but counted "
            "(paddle_tpu_kernel_fallbacks_total{kernel,reason}).  "
            "Read at BUILD time by build_lm_paged_decoder (like "
            "serving_kv_dtype) and at TRACE time by "
            "ParallelExecutor/moe_dense")
define_flag("serving_spec_k", 4,
            "default speculative-decoding draft length: how many "
            "tokens the draft model proposes per scheduler tick for "
            "the target to verify in ONE step_window dispatch "
            "(docs/serving.md 'Speculative decoding').  Used when a "
            "GenerationServer is given a draft model without an "
            "explicit spec_k (e.g. server_from_model_dir on a model "
            "dir with draft params); greedy outputs stay bit-identical "
            "for any k — k trades verify-step width against accept "
            "probability per window")
define_flag("flash_pack_heads", True,
            "fold head PAIRS into the 128-lane dim inside the flash "
            "kernel when head_dim == 64 (and the head count is even): "
            "loads/stores then move full-lane [block, 128] tiles and "
            "the online softmax runs per packed head on block-diagonal "
            "scores.  Measured step-level NEUTRAL on v5e (r5, "
            "RIDGE_r05.json): the d_head-64 penalty is the MXU "
            "contraction width of the per-head matmuls, which packing "
            "loads cannot fix — prefer d_head 128 architecturally.  "
            "Read at TRACE time like flash_min_seq_k")
