"""Program / Block / Operator / Variable graph IR.

TPU-native re-design of the reference's ProgramDesc machinery:
  * proto schema: /root/reference/paddle/fluid/framework/framework.proto:34-152
  * python builders: /root/reference/python/paddle/v2/fluid/framework.py
    (Variable :127, Operator :362, Block :630, Program :827, Parameter :988)

The IR is Python-native (dataclass-ish objects, serializable to plain dicts /
JSON) rather than protobuf: there is no C++ executor on the other side of a
pybind boundary — the executable artifact is an XLA computation produced by
tracing a Block (core/compiler.py), so the IR only needs to be cheap to build,
clone, rewrite (backward/transpilers) and hash (compile cache keys).
"""
from __future__ import annotations

import contextlib
import copy
import json
from typing import Dict, List, Optional, Sequence

import numpy as np

from . import registry
from .types import VarType, canonical_dtype

__all__ = [
    "Variable",
    "Parameter",
    "Operator",
    "Block",
    "Program",
    "EMPTY_VAR_NAMES",
    "default_main_program",
    "default_startup_program",
    "program_guard",
    "switch_main_program",
    "switch_startup_program",
    "unique_name",
    "grad_var_name",
    "pipeline_stage",
    "current_pipeline_stage",
]

GRAD_SUFFIX = "@GRAD"


def normalize_sharding(spec):
    """Canonical form of a GSPMD-style sharding annotation: a tuple with
    one entry per tensor dim — `None` (replicated dim), a mesh-axis name
    string, or a tuple of axis names (dim split over their product).
    Accepts jax PartitionSpec, lists, or the canonical form itself;
    returns None for "no annotation"."""
    if spec is None:
        return None
    if isinstance(spec, str):
        # a bare "dp" would iterate as ('d', 'p') — an unintended rank-2
        # split over nonexistent axes; demand the explicit per-dim form
        raise ValueError(
            f"sharding spec must have one entry per tensor dim — got the "
            f"bare string {spec!r}; write ({spec!r},) to shard dim 0")
    out = []
    for e in tuple(spec):
        if e is None:
            out.append(None)
        elif isinstance(e, str):
            out.append(e)
        elif isinstance(e, (list, tuple)):
            bad = [a for a in e if not isinstance(a, str)]
            if bad:
                raise ValueError(
                    f"sharding spec entry {e!r}: grouped axes must be "
                    "mesh-axis names")
            out.append(tuple(e))
        else:
            raise ValueError(
                f"sharding spec entry {e!r} is not None, an axis name, "
                "or a tuple of axis names")
    return tuple(out)


def sharding_axes(spec):
    """Flat list of mesh-axis names referenced by a normalized spec (a
    repeated name appears repeatedly — callers detect duplicates)."""
    axes = []
    for e in spec or ():
        if isinstance(e, str):
            axes.append(e)
        elif isinstance(e, tuple):
            axes.extend(e)
    return axes

# sentinel "no variable here" slot entries (reference: kEmptyVarName) —
# grad descs use them for inputs that need no gradient; every name-based
# walk (execution dispatch, backward, the analysis passes) skips them
EMPTY_VAR_NAMES = ("", "@EMPTY@")


def grad_var_name(name: str) -> str:
    return name + GRAD_SUFFIX


# ---------------------------------------------------------------------------
# unique names
# ---------------------------------------------------------------------------

_name_counters: Dict[str, int] = {}


def unique_name(prefix: str) -> str:
    idx = _name_counters.get(prefix, 0)
    _name_counters[prefix] = idx + 1
    return f"{prefix}_{idx}"


def reset_unique_names():
    _name_counters.clear()


# ---------------------------------------------------------------------------
# pipeline stage annotation
# ---------------------------------------------------------------------------

_pipeline_stage_stack: List[int] = []


class pipeline_stage:
    """`with fluid.pipeline_stage(i): ...` — tag the ops built inside the
    block with pipeline stage `i`.

    This is the DSL surface of pipeline parallelism: the reference made
    per-layer device placement a user-config feature of the framework
    (/root/reference/paddle/gserver/gradientmachines/ParallelNeuralNetwork.h,
    layer `deviceId` + the `parallel_nn` flag, utils/Flags.cpp:37); here the
    same reachability is a stage annotation on Program ops, consumed by
    parallel.PipelineExecutor which runs the annotated trunk as a GPipe
    schedule over a 'pp' mesh axis (parallel/pipeline.py).  The annotation
    is inert everywhere else — the serial Executor and ParallelExecutor
    ignore it, so one Program serves both execution styles.
    """

    def __init__(self, idx: int):
        self.idx = int(idx)

    def __enter__(self):
        _pipeline_stage_stack.append(self.idx)
        return self

    def __exit__(self, *exc):
        _pipeline_stage_stack.pop()
        return False


def current_pipeline_stage() -> Optional[int]:
    return _pipeline_stage_stack[-1] if _pipeline_stage_stack else None


# ---------------------------------------------------------------------------
# Variable
# ---------------------------------------------------------------------------


class Variable:
    """A named slot in a Block (reference framework.py:127).

    `shape` may contain -1 (batch / data-dependent dims); concrete shapes are
    only fixed when the executor binds real arrays.
    """

    def __init__(
        self,
        block: "Block",
        name: str,
        shape: Optional[Sequence[int]] = None,
        dtype: str = "float32",
        lod_level: int = 0,
        persistable: bool = False,
        stop_gradient: bool = False,
        type: str = VarType.LOD_TENSOR,
        initializer=None,
        donate: bool = False,
        sharding=None,
    ):
        self.block = block
        self.name = name
        self.shape = tuple(int(s) for s in shape) if shape is not None else None
        self.dtype = canonical_dtype(dtype) if dtype is not None else None
        self.lod_level = lod_level
        self.persistable = persistable
        self.stop_gradient = stop_gradient
        self.type = type
        self.initializer = initializer
        # donation hint: the executor may hand this feed's device buffer
        # to XLA as a donated input (memory_optimization_transpiler
        # .plan_donation validates the hint at build time; the
        # donation-safety analysis pass lints it)
        self.donate = bool(donate)
        # GSPMD-style sharding annotation (normalize_sharding form): one
        # entry per dim naming the mesh axis (or axis tuple) that dim is
        # split over, None = replicated.  Inert under the serial
        # executor; the spmd transpiler (parallel/spmd.py) propagates it
        # across ops and lowers the program onto a mesh, and the
        # sharding-consistency analysis pass lints it at build time.
        self.sharding = normalize_sharding(sharding)
        # op that produced this var most recently (set by append_op)
        self.op: Optional["Operator"] = None

    # -- sugar used by layers ------------------------------------------------
    @property
    def ndim(self):
        return len(self.shape) if self.shape is not None else None

    def astype(self, dtype):
        from .. import layers

        return layers.cast(self, dtype)

    def __add__(self, other):
        return _elementwise(self, other, "elementwise_add")

    __radd__ = __add__

    def __sub__(self, other):
        return _elementwise(self, other, "elementwise_sub")

    def __rsub__(self, other):
        from .. import layers

        # scalar - x == scale(x, -1) + scalar
        return layers.scale(self, scale=-1.0, bias=float(other))

    def __mul__(self, other):
        return _elementwise(self, other, "elementwise_mul")

    __rmul__ = __mul__

    def __truediv__(self, other):
        return _elementwise(self, other, "elementwise_div")

    def __rtruediv__(self, other):
        from .. import layers

        # scalar / x == scalar * reciprocal(x)
        return layers.scale(layers.reciprocal(self), scale=float(other))

    def to_dict(self):
        return {
            "name": self.name,
            "shape": list(self.shape) if self.shape is not None else None,
            "dtype": self.dtype,
            "lod_level": self.lod_level,
            "persistable": self.persistable,
            "stop_gradient": self.stop_gradient,
            "type": self.type,
            "is_parameter": isinstance(self, Parameter),
            "trainable": getattr(self, "trainable", None),
            "donate": self.donate,
            "sharding": (None if self.sharding is None
                         else [list(e) if isinstance(e, tuple) else e
                               for e in self.sharding]),
        }

    def __repr__(self):
        return (
            f"Var({self.name}, shape={self.shape}, dtype={self.dtype}, "
            f"type={self.type}{', persistable' if self.persistable else ''})"
        )


class Parameter(Variable):
    """A persistable, trainable Variable (reference framework.py:988)."""

    def __init__(self, block, name, shape, dtype, **kw):
        self.trainable = kw.pop("trainable", True)
        self.optimize_attr = kw.pop("optimize_attr", {"learning_rate": 1.0})
        self.regularizer = kw.pop("regularizer", None)
        self.gradient_clip_attr = kw.pop("gradient_clip_attr", None)
        self.do_model_average = kw.pop("do_model_average", None)
        # [{"type": "pruning", "sparsity_ratio": r}, ...] — reference
        # ParameterUpdaterHook.cpp (ParameterConfig.update_hooks)
        self.update_hooks = kw.pop("update_hooks", None)
        super().__init__(
            block, name, shape=shape, dtype=dtype, persistable=True, **kw
        )


# ---------------------------------------------------------------------------
# Operator
# ---------------------------------------------------------------------------


def _as_name_list(v) -> List[str]:
    if v is None:
        return []
    if isinstance(v, (list, tuple)):
        return [x.name if isinstance(x, Variable) else str(x) for x in v]
    return [v.name if isinstance(v, Variable) else str(v)]


class Operator:
    """An op desc: type + named input/output var lists + attrs.

    Reference framework.py:362 / framework.proto:104 (OpDesc).  Attrs may hold
    python scalars, lists, strings, numpy arrays, or Block indices (for
    control-flow sub-blocks, stored as {"__block__": idx}).
    """

    def __init__(self, block, type, inputs=None, outputs=None, attrs=None):
        self.block = block
        self.type = type
        self.inputs: Dict[str, List[str]] = {
            k: _as_name_list(v) for k, v in (inputs or {}).items()
        }
        self.outputs: Dict[str, List[str]] = {
            k: _as_name_list(v) for k, v in (outputs or {}).items()
        }
        self.attrs: Dict = dict(attrs or {})
        stage = current_pipeline_stage()
        if stage is not None:
            self.attrs.setdefault("pipeline_stage", stage)

    def input_names(self) -> List[str]:
        return [n for vs in self.inputs.values() for n in vs]

    def output_names(self) -> List[str]:
        return [n for vs in self.outputs.values() for n in vs]

    def input(self, slot) -> List[str]:
        return self.inputs.get(slot, [])

    def output(self, slot) -> List[str]:
        return self.outputs.get(slot, [])

    @property
    def dist_attr(self) -> Dict:
        """Distributed attributes of this op desc — a plain dict rider
        under attrs["dist_attr"] (so it serializes through
        to_dict/from_dict with every other attr).  Keys used by the
        spmd transpiler: "sharding" ({output name -> spec}, an op-level
        override of the propagated specs), "reduce_axes" (mesh axes the
        op's output carries a pending partial-sum over).  Reading never
        inserts the attr (op descs stay fingerprint-stable); write
        through set_dist_attr."""
        return self.attrs.get("dist_attr", {})

    def set_dist_attr(self, key: str, value) -> None:
        self.attrs.setdefault("dist_attr", {})[key] = value

    def sub_block(self, attr_name="sub_block") -> Optional["Block"]:
        ref = self.attrs.get(attr_name)
        if ref is None:
            return None
        idx = ref["__block__"] if isinstance(ref, dict) else int(ref)
        return self.block.program.blocks[idx]

    def to_dict(self):
        def enc_attr(v):
            if isinstance(v, np.ndarray):
                return {"__ndarray__": v.tolist(), "dtype": str(v.dtype)}
            return v

        return {
            "type": self.type,
            "inputs": self.inputs,
            "outputs": self.outputs,
            "attrs": {k: enc_attr(v) for k, v in self.attrs.items()},
        }

    def __repr__(self):
        ins = ", ".join(f"{k}={v}" for k, v in self.inputs.items())
        outs = ", ".join(f"{k}={v}" for k, v in self.outputs.items())
        return f"{{{self.type}: ({ins}) -> ({outs})}}"


# ---------------------------------------------------------------------------
# Block
# ---------------------------------------------------------------------------


class Block:
    """A straight-line list of ops + a var table (reference framework.py:630)."""

    def __init__(self, program: "Program", idx: int, parent_idx: int = -1):
        self.program = program
        self.idx = idx
        self.parent_idx = parent_idx
        self.vars: Dict[str, Variable] = {}
        self.ops: List[Operator] = []

    @property
    def parent(self) -> Optional["Block"]:
        if self.parent_idx < 0:
            return None
        return self.program.blocks[self.parent_idx]

    # -- vars ---------------------------------------------------------------
    def create_var(self, name=None, **kw) -> Variable:
        if name is None:
            name = unique_name("tmp")
        if name in self.vars:
            existing = self.vars[name]
            # a second create_var for the same name used to silently hand
            # back the existing var even when the caller asked for a
            # DIFFERENT shape/dtype — the caller then builds ops against
            # a type it never gets.  Explicitly conflicting kwargs raise.
            conflicts = []
            shape = kw.get("shape")
            if (shape is not None and existing.shape is not None
                    and tuple(int(s) for s in shape) != existing.shape):
                conflicts.append(
                    f"shape {list(shape)} vs existing "
                    f"{list(existing.shape)}")
            dtype = kw.get("dtype")
            if dtype is not None and existing.dtype is not None:
                if canonical_dtype(dtype) != existing.dtype:
                    conflicts.append(
                        f"dtype {dtype} vs existing {existing.dtype}")
            if conflicts:
                raise ValueError(
                    f"create_var({name!r}) collides with an existing "
                    f"variable in block {self.idx}: "
                    + "; ".join(conflicts)
                    + " — use a unique name (unique_name) or match the "
                    "existing declaration")
            return existing
        v = Variable(self, name, **kw)
        self.vars[name] = v
        return v

    def create_parameter(self, name, shape, dtype, **kw) -> Parameter:
        p = Parameter(self, name, shape, dtype, **kw)
        self.vars[name] = p
        return p

    def var(self, name: str) -> Variable:
        """Find var in this block or ancestors (scope-style lookup)."""
        b = self
        while b is not None:
            if name in b.vars:
                return b.vars[name]
            b = b.parent
        raise KeyError(f"variable '{name}' not found in block {self.idx}")

    def has_var(self, name: str) -> bool:
        try:
            self.var(name)
            return True
        except KeyError:
            return False

    def all_parameters(self) -> List[Parameter]:
        return [v for v in self.vars.values() if isinstance(v, Parameter)]

    # -- ops ----------------------------------------------------------------
    def append_op(self, type, inputs=None, outputs=None, attrs=None) -> Operator:
        op = Operator(self, type, inputs, outputs, attrs)
        self.ops.append(op)
        self._post_insert(op)
        return op

    def prepend_op(self, type, inputs=None, outputs=None, attrs=None) -> Operator:
        op = Operator(self, type, inputs, outputs, attrs)
        self.ops.insert(0, op)
        self._post_insert(op)
        return op

    def insert_op(self, index, type, inputs=None, outputs=None, attrs=None):
        op = Operator(self, type, inputs, outputs, attrs)
        self.ops.insert(index, op)
        self._post_insert(op)
        return op

    def _post_insert(self, op: Operator):
        self.program.bump_version()
        # auto-create missing output vars (backward/transpiler convenience)
        for n in op.output_names():
            if n not in ("", "@EMPTY@") and not self.has_var(n):
                self.create_var(name=n, dtype=None)
        # record producer + run build-time shape inference when available
        info = None
        try:
            info = registry.get_op_info(op.type)
        except KeyError:
            pass
        if info is not None:
            from . import shape_inference

            try:
                if info.infer_shape is not None and info.type == op.type:
                    info.infer_shape(op, self)
                elif op.type.endswith("_grad"):
                    shape_inference.infer_grad_shapes(op, self)
                else:
                    shape_inference.default_infer_shape(op, self)
            except KeyError:
                pass  # vars created later (e.g. grad rewrites fill them in)
        for n in op.output_names():
            if n in self.vars:
                self.vars[n].op = op

    def to_dict(self):
        return {
            "idx": self.idx,
            "parent_idx": self.parent_idx,
            "vars": [v.to_dict() for v in self.vars.values()],
            "ops": [o.to_dict() for o in self.ops],
        }


# ---------------------------------------------------------------------------
# Program
# ---------------------------------------------------------------------------


class Program:
    """A list of Blocks; block 0 is global (reference framework.py:827)."""

    def __init__(self):
        self.blocks: List[Block] = [Block(self, 0)]
        self._current_block_idx = 0
        self.seed = 0  # program-level RNG seed (0 = derive from executor)
        # declared device-mesh axes ({name: size}) for the sharding
        # annotations on this program's vars — set by the user surface
        # (layers.set_program_mesh) or the spmd transpiler; the
        # sharding-consistency analysis pass validates specs against it
        self.mesh_axes: Optional[Dict[str, int]] = None
        self._version = 0  # bumped on mutation -> invalidates compile cache

    # -- block management ---------------------------------------------------
    @property
    def current_block(self) -> Block:
        return self.blocks[self._current_block_idx]

    def global_block(self) -> Block:
        return self.blocks[0]

    def create_block(self, parent_idx=None) -> Block:
        parent_idx = (
            self._current_block_idx if parent_idx is None else parent_idx
        )
        b = Block(self, len(self.blocks), parent_idx)
        self.blocks.append(b)
        self._current_block_idx = b.idx
        return b

    def rollback(self):
        self._current_block_idx = self.current_block.parent_idx

    @contextlib.contextmanager
    def block_guard(self, block: Block):
        prev = self._current_block_idx
        self._current_block_idx = block.idx
        try:
            yield block
        finally:
            self._current_block_idx = prev

    # -- mutation tracking ---------------------------------------------------
    def bump_version(self):
        self._version += 1

    def fingerprint(self) -> str:
        """Stable hash of the whole program for compile-cache keys."""
        payload = json.dumps(self.to_dict(), sort_keys=True, default=str)
        import hashlib

        return hashlib.sha1(payload.encode()).hexdigest()

    # -- static analysis -----------------------------------------------------
    def verify(self, level: Optional[str] = "error", passes=None,
               feed_names=None, fetch_names=None):
        """Run the static analyzer (paddle_tpu.analysis) over this
        program and return every Diagnostic.

        `level`: raise ProgramVerificationError when any diagnostic is
        at or above this severity ("error" default; "warn"/"warning",
        "info"); None or "off" never raises — inspect the returned list.
        `passes`: restrict to specific pass ids (docs/analysis.md).
        `feed_names`/`fetch_names`: optional runtime context that
        sharpens the def-before-use and dead-op passes.
        """
        from ..analysis import verify_program

        return verify_program(self, level=level, passes=passes,
                              feed_names=feed_names,
                              fetch_names=fetch_names)

    # -- clone / serialization ----------------------------------------------
    def clone(self, for_test: bool = False) -> "Program":
        """Deep-copy the program.  `for_test=True` flips is_test attrs
        (dropout/batch_norm switch to inference behavior), mirroring
        reference framework.py Program.clone."""
        p = copy.deepcopy(self)
        if for_test:
            for b in p.blocks:
                for op in b.ops:
                    if "is_test" in _op_declared_attrs(op.type):
                        op.attrs["is_test"] = True
        p.bump_version()
        return p

    def to_dict(self):
        d = {"blocks": [b.to_dict() for b in self.blocks],
             "seed": self.seed}
        if self.mesh_axes is not None:
            d["mesh_axes"] = dict(self.mesh_axes)
        return d

    @classmethod
    def from_dict(cls, d: dict) -> "Program":
        """Reconstruct a Program from `to_dict()` output — the deserialization
        half of the model format (reference: ProgramDesc parsed back from the
        `__model__` protobuf in inference/io.cc:?Load; here the schema is
        JSON, framework.py to_dict)."""
        p = cls()
        p.seed = d.get("seed", 0)
        ma = d.get("mesh_axes")
        p.mesh_axes = ({str(k): int(v) for k, v in ma.items()}
                       if ma is not None else None)
        # materialize blocks first so sub_block attr refs resolve
        for bd in d["blocks"][1:]:
            b = Block(p, bd["idx"], bd.get("parent_idx", -1))
            p.blocks.append(b)
        for bd in d["blocks"]:
            b = p.blocks[bd["idx"]]
            for vd in bd.get("vars", []):
                kw = dict(
                    shape=vd.get("shape"),
                    dtype=vd.get("dtype"),
                    lod_level=vd.get("lod_level", 0),
                    persistable=vd.get("persistable", False),
                    stop_gradient=vd.get("stop_gradient", False),
                    type=vd.get("type", VarType.LOD_TENSOR),
                    donate=vd.get("donate", False),
                    sharding=vd.get("sharding"),
                )
                if vd.get("is_parameter"):
                    kw.pop("persistable")
                    v = Parameter(b, vd["name"], kw.pop("shape"),
                                  kw.pop("dtype"),
                                  trainable=vd.get("trainable", True), **kw)
                else:
                    v = Variable(b, vd["name"], **kw)
                b.vars[vd["name"]] = v
            for od in bd.get("ops", []):
                attrs = {
                    k: _dec_attr(v) for k, v in od.get("attrs", {}).items()
                }
                op = Operator(b, od["type"], od.get("inputs"),
                              od.get("outputs"), attrs)
                b.ops.append(op)
                for n in op.output_names():
                    if n in b.vars:
                        b.vars[n].op = op
        p._current_block_idx = 0
        p.bump_version()
        return p

    def __repr__(self):
        lines = []
        for b in self.blocks:
            lines.append(f"-- block {b.idx} (parent {b.parent_idx}) --")
            lines.extend(f"  {op}" for op in b.ops)
        return "\n".join(lines)

    def list_vars(self):
        for b in self.blocks:
            yield from b.vars.values()


def _dec_attr(v):
    if isinstance(v, dict) and "__ndarray__" in v:
        return np.asarray(v["__ndarray__"], dtype=np.dtype(v["dtype"]))
    return v


def _op_declared_attrs(type):
    try:
        return registry.get_op_info(type).attrs
    except KeyError:
        return {}


# ---------------------------------------------------------------------------
# default programs + guards (reference framework.py:1046-1120)
# ---------------------------------------------------------------------------

_main_program = Program()
_startup_program = Program()


def default_main_program() -> Program:
    return _main_program


def default_startup_program() -> Program:
    return _startup_program


def switch_main_program(p: Program) -> Program:
    global _main_program
    prev, _main_program = _main_program, p
    return prev


def switch_startup_program(p: Program) -> Program:
    global _startup_program
    prev, _startup_program = _startup_program, p
    return prev


@contextlib.contextmanager
def program_guard(main_program: Program, startup_program: Program = None):
    prev_main = switch_main_program(main_program)
    prev_startup = None
    if startup_program is not None:
        prev_startup = switch_startup_program(startup_program)
    try:
        yield
    finally:
        switch_main_program(prev_main)
        if prev_startup is not None:
            switch_startup_program(prev_startup)


def _elementwise(x: Variable, y, op_type: str) -> Variable:
    from .. import layers

    if not isinstance(y, Variable):
        y = layers.fill_constant(
            shape=[1], dtype=x.dtype, value=float(y)
        )
    fn = getattr(layers, op_type)
    return fn(x, y)
