"""Shared op execution: dispatch, contexts, and the generic VJP gradient.

This module is the TPU-native replacement for the reference's
`OperatorWithKernel::RunImpl` dispatch chain
(/root/reference/paddle/fluid/framework/operator.cc:494-570): instead of
choosing a (place, layout, dtype, library) kernel at every step and
data-transforming inputs between kernel types, a single jax lowering per op is
executed either eagerly (interpreter) or under a trace (core/compiler.py) —
XLA owns layout, fusion and device placement.

Gradient ops named "<type>_grad" with no explicit lowering are executed by
`jax.vjp` over the forward lowering (`generic_grad_lower`), which makes every
registered op differentiable by construction.  The reference instead requires
a hand-written grad kernel per op (op_registry.h REGISTER_OP grad class).
"""
from __future__ import annotations

from typing import Dict, List

import jax
import jax.numpy as jnp

from . import registry
from .lod import LoDTensor, SelectedRows, TensorArray

GRAD = "@GRAD"


# ---------------------------------------------------------------------------
# pytree registration so LoD/sparse values flow through jit/vjp transparently
# ---------------------------------------------------------------------------

jax.tree_util.register_pytree_node(
    LoDTensor,
    lambda t: ((t.data,), t.lod),
    lambda lod, kids: LoDTensor(kids[0], lod),
)
jax.tree_util.register_pytree_node(
    SelectedRows,
    lambda s: ((s.rows, s.value), s.height),
    lambda height, kids: SelectedRows(kids[0], kids[1], height),
)
jax.tree_util.register_pytree_node(
    TensorArray,
    lambda a: (tuple(a.tensors), None),
    lambda _, kids: TensorArray(list(kids)),
)


# ---------------------------------------------------------------------------
# execution context
# ---------------------------------------------------------------------------


class ExecContext:
    """Passed to every lowering.  Provides deterministic per-op PRNG keys and
    access to host-side facilities for interpreter-only ops.

    Key derivation: run_op folds a stable hash of the op's identity
    (type + output var names; for a generic grad op, its FORWARD op's
    identity) into the step key, so (a) randomness is independent of op
    order, and (b) the VJP re-trace of a random forward op (e.g. nce)
    draws exactly the forward's samples."""

    def __init__(self, rng_key=None, scope=None, executor=None, compiled=False):
        self._rng_key = rng_key
        self._rng_counter = 0
        self.scope = scope
        self.executor = executor
        self.compiled = compiled

    def rng(self):
        """A fresh PRNG key, deterministic per (base key, call index)."""
        if self._rng_key is None:
            self._rng_key = jax.random.key(0)
        k = jax.random.fold_in(self._rng_key, self._rng_counter)
        self._rng_counter += 1
        return k

    def child(self, tag_hash: int) -> "ExecContext":
        """Per-op context: base key folded with the op-identity hash."""
        base = self._rng_key if self._rng_key is not None else jax.random.key(0)
        c = ExecContext(jax.random.fold_in(base, tag_hash & 0x7FFFFFFF),
                        self.scope, self.executor, self.compiled)
        return c

    def pure(self) -> "ExecContext":
        """Context for re-tracing a forward op inside its VJP: same rng
        stream restarted so forward recomputation matches (XLA CSEs it).
        Carries op/env so sub-block ops (dynamic_rnn) stay resolvable."""
        c = ExecContext(self._rng_key, self.scope, self.executor, self.compiled)
        c.op = getattr(self, "op", None)
        c.env = getattr(self, "env", None)
        c.root = getattr(self, "root", None)
        return c


# ---------------------------------------------------------------------------
# env protocol: interpreter uses Scope, tracer uses plain dict
# ---------------------------------------------------------------------------


class DictEnv:
    def __init__(self, init=None):
        self.d = dict(init or {})
        self.written = set()

    def get(self, name):
        return self.d.get(name)

    def set(self, name, value):
        self.d[name] = value
        self.written.add(name)

    def has(self, name):
        return name in self.d


class ScopeEnv:
    def __init__(self, scope):
        self.scope = scope
        self.written = set()

    def get(self, name):
        try:
            return self.scope.find_var(name)
        except KeyError:
            return None

    def set(self, name, value):
        self.scope.set_var(name, value)
        self.written.add(name)

    def has(self, name):
        return self.scope.has_var(name)


# ---------------------------------------------------------------------------
# dispatch
# ---------------------------------------------------------------------------

from .framework import EMPTY_VAR_NAMES as _EMPTY


def gather_inputs(op, env) -> Dict[str, List]:
    return {
        slot: [env.get(n) if n not in _EMPTY else None for n in names]
        for slot, names in op.inputs.items()
    }


def scatter_outputs(op, env, outs: Dict[str, List]):
    for slot, names in op.outputs.items():
        vals = outs.get(slot)
        if vals is None:
            continue
        if not isinstance(vals, (list, tuple)):
            vals = [vals]
        for name, val in zip(names, vals):
            if name not in _EMPTY:
                env.set(name, val)


def _op_rng_tag(op, info) -> str:
    """Stable op identity for PRNG key derivation.  A generic grad op gets
    its FORWARD op's tag (forward output names appear among the grad op's
    input slots), so VJP recomputation samples the same randomness."""
    if info.type != op.type:  # generic "<fwd>_grad"
        names = tuple(n for s in info.outputs for n in op.inputs.get(s, []))
        return f"{info.type}:{names}"
    return f"{op.type}:{tuple(op.output_names())}"


def run_op(ctx: ExecContext, op, env):
    """Execute one op desc against `env` (eager or traced)."""
    ins = gather_inputs(op, env)
    t = op.type
    try:
        info = registry.get_op_info(t)
    except KeyError:
        raise NotImplementedError(f"op '{t}' has no lowering") from None
    import zlib

    tag_hash = zlib.crc32(_op_rng_tag(op, info).encode())
    # PipelineExecutor's ONE traced stage body executes stage 0's op
    # descs for EVERY stage; tag_lookup substitutes the per-stage op's
    # serial identity (a traced int selected by the stage index) so a
    # random op in stage s draws exactly what the serial executor's
    # stage-s op would — see pipeline_program._make_jit_step
    lookup = getattr(ctx, "tag_lookup", None)
    if lookup is not None:
        traced_tag = lookup(op)
        if traced_tag is not None:
            tag_hash = traced_tag
    op_ctx = ctx.child(tag_hash)
    op_ctx.op = op
    op_ctx.env = env
    op_ctx.root = ctx
    # named scope per op: XLA op metadata carries "<type>:<first output>",
    # so device profiles/HLO dumps attribute fusions back to program ops
    # (reference executor.cc:124 wraps each op run in a RecordEvent; inside
    # a jit trace the scope name is the compile-time analogue)
    outs_names = op.output_names()
    scope = f"{t}:{outs_names[0]}" if outs_names else t
    with jax.named_scope(scope):
        if info.type == t:  # explicit lowering (fwd op, or custom grad)
            outs = info.lower(op_ctx, ins, {**info.attrs, **op.attrs})
        else:  # generic "<fwd>_grad" resolved to forward info
            outs = generic_grad_lower(op_ctx, ins,
                                      {**info.attrs, **op.attrs}, info)
    scatter_outputs(op, env, outs)


# ---------------------------------------------------------------------------
# generic VJP gradient
# ---------------------------------------------------------------------------


def _leaf_is_float(v) -> bool:
    leaves = jax.tree_util.tree_leaves(v)
    return bool(leaves) and all(
        jnp.issubdtype(jnp.asarray(x).dtype, jnp.floating) for x in leaves
    )


def generic_grad_lower(ctx, ins, attrs, fwd_info):
    """Grad-op convention (see backward.py): inputs = forward input slots +
    forward output slots + "<out_slot>@GRAD" cotangents; outputs =
    "<in_slot>@GRAD".  Missing cotangents must have been filled with
    fill_zeros_like by the backward builder."""
    fwd_ins = {
        s: ins[s] for s in fwd_info.inputs if s in ins and ins[s] is not None
    }
    # which inputs to differentiate
    if fwd_info.diff_inputs is not None:
        diff_slots = [s for s in fwd_info.diff_inputs if s in fwd_ins]
    else:
        diff_slots = [s for s in fwd_ins if _leaf_is_float(fwd_ins[s])]
    # which outputs carry cotangents
    if fwd_info.diff_outputs is not None:
        out_slots = [s for s in fwd_info.diff_outputs if s + GRAD in ins]
    else:
        out_slots = [s for s in fwd_info.outputs if s + GRAD in ins]
    if not diff_slots or not out_slots:
        return {}

    pure_ctx = ctx.pure()

    def fwd_fn(diff_vals):
        full = dict(fwd_ins)
        full.update(diff_vals)
        outs = fwd_info.lower(pure_ctx, full, attrs)
        res = {}
        for s in out_slots:
            v = outs[s]
            res[s] = v if isinstance(v, (list, tuple)) else [v]
        return res

    primals = {s: fwd_ins[s] for s in diff_slots}
    out_primals, vjp_fn = jax.vjp(fwd_fn, primals)
    cotangents = {}
    for s in out_slots:
        v = ins[s + GRAD]
        cotangents[s] = list(v) if isinstance(v, (list, tuple)) else [v]
    # jax.vjp demands cotangent avals match the primal outputs exactly;
    # under amp a downstream grad op may hand back a bf16 cotangent for
    # an f32 forward output (or vice versa) — cast leaf-wise to match
    def _cast_like(c, p):
        pd, cd = data_of(p), data_of(c)
        if (pd is None or cd is None or not hasattr(cd, "dtype")
                or cd.dtype == pd.dtype
                or not jnp.issubdtype(pd.dtype, jnp.floating)):
            return c
        if isinstance(c, LoDTensor):
            return LoDTensor(cd.astype(pd.dtype), c.lod)
        return cd.astype(pd.dtype)

    for s in out_slots:
        cotangents[s] = [_cast_like(c, p)
                         for c, p in zip(cotangents[s], out_primals[s])]
    (gin,) = vjp_fn(cotangents)
    return {s + GRAD: gin[s] for s in diff_slots}


# ---------------------------------------------------------------------------
# lowering helper utilities (imported by op modules)
# ---------------------------------------------------------------------------


def one(ins, slot):
    """Single (required) input value for a slot; unwraps length-1 lists."""
    v = ins.get(slot)
    if v is None:
        return None
    if isinstance(v, (list, tuple)):
        return v[0] if v else None
    return v


def many(ins, slot):
    v = ins.get(slot)
    if v is None:
        return []
    return list(v) if isinstance(v, (list, tuple)) else [v]


def data_of(v):
    """Dense array behind a value (LoDTensor -> .data)."""
    if isinstance(v, LoDTensor):
        return v.data
    return v


def with_lod_of(v, out_data):
    """Rewrap out_data with v's LoD if v carried one."""
    if isinstance(v, LoDTensor):
        return LoDTensor(out_data, v.lod)
    return out_data
