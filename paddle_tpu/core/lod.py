"""LoDTensor / SelectedRows / TensorArray runtime values.

Reference:
  * LoDTensor — /root/reference/paddle/fluid/framework/lod_tensor.h (+ design
    note lod_tensor.md): a dense tensor whose rows pack variable-length,
    possibly nested sequences, with a level-of-detail offset table instead of
    padding.
  * SelectedRows — framework/selected_rows.h:1-60: sparse row-slice gradient
    representation (embedding grads).
  * LoDTensorArray — used by dynamic-RNN / beam-search machinery.

TPU mapping: the *API* keeps LoD semantics (flat concatenated rows + offset
table, "no padding"); sequence ops lower to dense+segment-id/mask XLA code.
The offset table is host-side metadata (a tuple of python int tuples) — it is
part of the compile-cache key, so each length-bucket compiles once (the
bucketing discipline replaces the reference's per-batch dynamic shapes).
"""
from __future__ import annotations

from typing import List, Sequence, Tuple

import numpy as np


class LoDTensor:
    """data: jax/numpy array whose dim-0 is the packed row axis; lod: nested
    offset tables, outermost level first, e.g. [[0, 2, 5]] packs two sequences
    of lengths 2 and 3."""

    __slots__ = ("data", "lod")

    def __init__(self, data, lod: Sequence[Sequence[int]] = ()):
        self.data = data
        self.lod: Tuple[Tuple[int, ...], ...] = tuple(
            tuple(int(x) for x in level) for level in lod
        )

    # -- helpers -------------------------------------------------------------
    @property
    def shape(self):
        return tuple(self.data.shape)

    @property
    def dtype(self):
        return self.data.dtype

    def seq_lens(self, level: int = 0) -> List[int]:
        offs = self.lod[level]
        return [offs[i + 1] - offs[i] for i in range(len(offs) - 1)]

    def num_sequences(self, level: int = 0) -> int:
        return len(self.lod[level]) - 1 if self.lod else self.data.shape[0]

    def recursive_seq_lens(self):
        return [self.seq_lens(i) for i in range(len(self.lod))]

    def segment_ids(self, level: int = 0) -> np.ndarray:
        """Row -> sequence index map for segment-sum style lowering."""
        offs = self.lod[level]
        out = np.zeros(offs[-1], dtype=np.int32)
        for i in range(len(offs) - 1):
            out[offs[i] : offs[i + 1]] = i
        return out

    def __repr__(self):
        return f"LoDTensor(shape={self.shape}, lod={self.lod})"


def lod_from_seq_lens(seq_lens: Sequence[int]) -> Tuple[int, ...]:
    offs = [0]
    for n in seq_lens:
        offs.append(offs[-1] + int(n))
    return tuple(offs)


def create_lod_tensor(data, recursive_seq_lens=(), place=None) -> LoDTensor:
    """Build a LoDTensor from flat data + per-level sequence lengths
    (mirrors reference fluid.create_lod_tensor)."""
    lod = [lod_from_seq_lens(lv) for lv in recursive_seq_lens]
    return LoDTensor(np.asarray(data), lod)


class SelectedRows:
    """Sparse row slices: `rows[i]` is the row index into the dense var of
    height `height`; `value[i]` is that row's data.  Duplicate rows allowed
    (summed on materialization), matching reference semantics."""

    __slots__ = ("rows", "value", "height")

    def __init__(self, rows, value, height: int):
        self.rows = rows  # int array [n]
        self.value = value  # [n, ...] array
        self.height = int(height)

    def to_dense(self):
        import jax.numpy as jnp

        dense_shape = (self.height,) + tuple(self.value.shape[1:])
        out = jnp.zeros(dense_shape, self.value.dtype)
        return out.at[self.rows].add(self.value)

    def __repr__(self):
        return (
            f"SelectedRows(height={self.height}, n={len(self.rows)}, "
            f"row_dim={tuple(self.value.shape[1:])})"
        )


class TensorArray:
    """LoDTensorArray: ordered list of tensors (dynamic RNN outputs,
    beam-search trajectories)."""

    __slots__ = ("tensors",)

    def __init__(self, tensors=None):
        self.tensors: List = list(tensors or [])

    def append(self, t):
        self.tensors.append(t)

    def __len__(self):
        return len(self.tensors)

    def __getitem__(self, i):
        return self.tensors[i]

    def __repr__(self):
        return f"TensorArray(len={len(self.tensors)})"


class Tensor:
    """Host-side tensor container with the pybind Tensor surface
    (reference pybind.cc:73 — `t = fluid.Tensor(); t.set(arr, place)`).
    The runtime's actual tensors are jax arrays; this exists for feed
    construction parity."""

    def __init__(self):
        self._value = None

    def set(self, array, place=None):
        import numpy as np

        del place
        self._value = np.asarray(array)

    def shape(self):
        return list(self._value.shape) if self._value is not None else []

    def __array__(self, dtype=None):
        import numpy as np

        return np.asarray(self._value, dtype)
