"""Core dtype / variable-type vocabulary.

TPU-native re-design of the reference's proto enums
(/root/reference/paddle/fluid/framework/framework.proto:91-117 VarDesc.VarType,
:142 LoDTensorDesc).  Dtypes are plain strings mapped onto numpy/jax dtypes;
variable "types" describe what a Variable holds at runtime.
"""
from __future__ import annotations

import numpy as np

# ---------------------------------------------------------------------------
# dtypes
# ---------------------------------------------------------------------------

_DTYPES = {
    "float16": np.float16,
    "bfloat16": None,  # filled lazily from ml_dtypes to avoid hard import
    "float32": np.float32,
    "float64": np.float64,
    "int8": np.int8,
    "uint8": np.uint8,
    "int16": np.int16,
    "int32": np.int32,
    "int64": np.int64,
    "bool": np.bool_,
}

FLOAT_DTYPES = ("float16", "bfloat16", "float32", "float64")
INT_DTYPES = ("int8", "uint8", "int16", "int32", "int64")


def np_dtype(name):
    """Canonical name -> numpy dtype (bfloat16 via ml_dtypes)."""
    name = canonical_dtype(name)
    if name == "bfloat16":
        import ml_dtypes

        return np.dtype(ml_dtypes.bfloat16)
    return np.dtype(_DTYPES[name])


def canonical_dtype(d) -> str:
    """Accept strings / numpy dtypes / jax arrays' dtypes -> canonical name."""
    if isinstance(d, str):
        if d in _DTYPES:
            return d
        # allow numpy-style names like '<f4'
        return np.dtype(d).name
    name = np.dtype(d).name
    if name == "bfloat16":
        return "bfloat16"
    if name not in _DTYPES:
        raise ValueError(f"unsupported dtype {d!r}")
    return name


def is_float_dtype(d) -> bool:
    return canonical_dtype(d) in FLOAT_DTYPES


# ---------------------------------------------------------------------------
# variable types (what a Variable holds)
# ---------------------------------------------------------------------------


class VarType:
    LOD_TENSOR = "lod_tensor"          # dense tensor (+ optional LoD)
    SELECTED_ROWS = "selected_rows"    # sparse row-slices (embedding grads)
    LOD_TENSOR_ARRAY = "tensor_array"  # list of tensors (dynamic RNN states)
    LOD_RANK_TABLE = "lod_rank_table"  # sequence-length sort table
    STEP_SCOPES = "step_scopes"        # control-flow local scopes
    READER = "reader"                  # data pipeline handle
    RAW = "raw"                        # opaque python object
    FEED_MINIBATCH = "feed_minibatch"
    FETCH_LIST = "fetch_list"
