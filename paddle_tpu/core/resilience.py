"""Unified resilience layer: retry/backoff policies + fault injection.

Reference: the Go master re-dispatches timed-out tasks and snapshots its
queues (/root/reference/go/master/service.go checkTimeoutFunc), and the
Go pserver checkpoints its shard for crash recovery
(go/pserver/service.go:120-203).  Those recovery paths were exercised by
killing processes under a supervisor; this module gives our reproduction
the same two primitives, shared by every networked/durable subsystem:

  * `RetryPolicy` — exponential backoff with jitter, an attempt cap and
    an overall deadline.  Each knob is overridable per subsystem via
    ``PADDLE_TPU_<PREFIX>_<KNOB>`` environment variables (prefixes:
    ``MASTER_RETRY``, ``PSERVER_RETRY``, ``DOWNLOAD_RETRY``,
    ``REGISTRY_RETRY`` — RegistryClient heartbeat/resolve roundtrips —
    and ``CLUSTER_RETRY`` — ClusterClient view roundtrips; the bare
    ``RETRY`` prefix is the cross-subsystem fallback).
  * `FaultInjector` — process-local chaos hooks compiled into the hot
    paths (no-ops when no rules are armed).  Call sites `fire(site)` to
    give the injector a chance to drop the connection / delay, or
    `mangle(site, data)` to let it truncate/corrupt outgoing bytes.
    Rules come from test code (`fault_injector().inject(...)`) or from
    the ``PADDLE_TPU_FAULTS`` environment variable, so chaos runs work
    on unmodified entry points.

Injection sites currently wired (see docs/resilience.md):
  master.connect, master.send, pserver.connect, pserver.request,
  pserver.send, dataset.download, serving.dispatch, trainer.iteration,
  checkpoint.save, cluster.rebalance (start of a view change),
  cluster.migrate (per shard-migration source group)
"""
from __future__ import annotations

import fnmatch
import logging
import os
import random
import threading
import time
from typing import Callable, Dict, List, Optional

from ..observability import metrics as obs_metrics

# retries and injected faults used to be invisible until a policy
# exhausted — every retry, backoff sleep and fired fault now emits a
# structured warning here (configure/silence via the standard logging
# tree) and counts in the process metrics registry
_LOG = logging.getLogger("paddle_tpu.resilience")

_M_RETRY_ATTEMPTS = obs_metrics.counter(
    "paddle_tpu_resilience_retry_attempts_total",
    "failed attempts recorded by retry policies")
_M_BACKOFF_SECONDS = obs_metrics.counter(
    "paddle_tpu_resilience_backoff_seconds_total",
    "seconds slept in retry backoff")
_M_EXHAUSTED = obs_metrics.counter(
    "paddle_tpu_resilience_retries_exhausted_total",
    "RetryError raises (attempt/deadline budget exhausted)")
_M_FAULTS = obs_metrics.counter(
    "paddle_tpu_resilience_faults_fired_total",
    "chaos faults fired, by injection site and kind",
    ("site", "kind"))

__all__ = [
    "RetryPolicy",
    "RetryState",
    "RetryError",
    "FaultInjector",
    "FaultRule",
    "FaultError",
    "fault_injector",
    "sched_fault_armed",
]


# ---------------------------------------------------------------------------
# retry / backoff
# ---------------------------------------------------------------------------


class RetryError(OSError):
    """A RetryPolicy ran out of attempts or deadline.  Subclasses OSError
    so existing `except OSError` handlers around networked calls keep
    working; the message always carries attempt count and elapsed time."""

    def __init__(self, what: str, attempts: int, elapsed: float,
                 last_error: Optional[BaseException] = None):
        self.attempts = attempts
        self.elapsed = elapsed
        self.last_error = last_error
        detail = f": {type(last_error).__name__}: {last_error}" \
            if last_error is not None else ""
        super().__init__(
            f"{what} (gave up after {attempts} attempt"
            f"{'s' if attempts != 1 else ''} over {elapsed:.2f}s{detail})")
        _M_EXHAUSTED.inc()
        _LOG.warning("retry exhausted: %s", self)


class RetryPolicy:
    """Exponential backoff + jitter + overall deadline.

    delay(n) = min(max_delay, base_delay * multiplier**(n-1)), scaled by
    a uniform jitter factor in [1-jitter, 1+jitter].  A call sequence
    stops at `max_attempts` attempts or when `deadline` seconds have
    elapsed since the first attempt, whichever comes first; either is
    disabled by passing None.

    `sleep`/`clock`/`rng` are injectable for deterministic tests.
    """

    _ENV_FIELDS = ("max_attempts", "base_delay", "max_delay", "multiplier",
                   "jitter", "deadline")

    def __init__(self, max_attempts: Optional[int] = 8,
                 base_delay: float = 0.2, max_delay: float = 5.0,
                 multiplier: float = 2.0, jitter: float = 0.25,
                 deadline: Optional[float] = 60.0,
                 sleep: Callable[[float], None] = time.sleep,
                 clock: Callable[[], float] = time.monotonic,
                 rng: Optional[random.Random] = None):
        if max_attempts is not None and max_attempts < 1:
            raise ValueError(f"max_attempts must be >= 1, got {max_attempts}")
        self.max_attempts = max_attempts
        self.base_delay = float(base_delay)
        self.max_delay = float(max_delay)
        self.multiplier = float(multiplier)
        self.jitter = float(jitter)
        self.deadline = deadline
        self._sleep = sleep
        self._clock = clock
        self._rng = rng or random.Random()

    @classmethod
    def from_env(cls, prefix: str = "RETRY", **defaults) -> "RetryPolicy":
        """Build a policy whose knobs read ``PADDLE_TPU_<prefix>_<KNOB>``
        env vars, falling back to ``PADDLE_TPU_RETRY_<KNOB>`` and then to
        the passed/ctor defaults.  "none"/"inf" disable max_attempts or
        deadline."""
        kw = dict(defaults)
        for field in cls._ENV_FIELDS:
            for p in (prefix, "RETRY"):
                raw = os.environ.get(f"PADDLE_TPU_{p}_{field.upper()}")
                if raw is None or not raw.strip():
                    continue  # unset/empty: fall through, keep defaults
                raw = raw.strip()
                if raw.lower() in ("none", "inf"):
                    # only the cap-style knobs are disableable; "none" on
                    # e.g. MULTIPLIER keeps the default rather than
                    # poisoning the constructor with a None float
                    if field in ("max_attempts", "deadline"):
                        kw[field] = None
                elif field == "max_attempts":
                    kw[field] = int(raw)
                else:
                    kw[field] = float(raw)
                break
        return cls(**kw)

    def delay(self, attempt: int) -> float:
        """Backoff before attempt `attempt`+1 (attempt counts from 1)."""
        d = min(self.max_delay,
                self.base_delay * self.multiplier ** (attempt - 1))
        if self.jitter:
            d *= 1.0 + self._rng.uniform(-self.jitter, self.jitter)
        return max(d, 0.0)

    def begin(self) -> "RetryState":
        return RetryState(self)

    def call(self, fn: Callable, retry_on=(OSError,),
             what: str = "operation failed"):
        """Run `fn()` until it returns; exceptions in `retry_on` back off
        and retry, anything else propagates.  Raises RetryError (chained
        to the last error) when the policy is exhausted."""
        state = self.begin()
        while True:
            try:
                return fn()
            except retry_on as e:
                state.record(e, what=what)
                state.sleep()


class RetryState:
    """One retry sequence: tracks attempts + elapsed, raises RetryError
    on exhaustion.  Usage:

        state = policy.begin()
        while True:
            try:
                return do_io()
            except OSError as e:
                state.record(e, what="master at host:port unreachable")
                state.sleep()
    """

    def __init__(self, policy: RetryPolicy):
        self.policy = policy
        self.attempts = 0
        self._start = policy._clock()
        self._next_delay = 0.0

    @property
    def elapsed(self) -> float:
        return self.policy._clock() - self._start

    def record(self, err: Optional[BaseException] = None,
               what: str = "operation failed"):
        """Count a failed attempt; raise RetryError when no budget is
        left for another one."""
        self.attempts += 1
        _M_RETRY_ATTEMPTS.inc()
        p = self.policy
        delay = p.delay(self.attempts)
        exhausted = (p.max_attempts is not None
                     and self.attempts >= p.max_attempts)
        if not exhausted and p.deadline is not None:
            exhausted = self.elapsed + delay >= p.deadline
        if exhausted:
            raise RetryError(what, self.attempts, self.elapsed,
                             last_error=err) from err
        _LOG.warning(
            "%s — attempt %d failed (%s: %s), retrying in %.2fs "
            "(%.2fs elapsed)", what, self.attempts,
            type(err).__name__ if err is not None else "error", err,
            delay, self.elapsed)
        self._next_delay = delay

    def sleep(self):
        if self._next_delay > 0:
            _M_BACKOFF_SECONDS.inc(self._next_delay)
            self.policy._sleep(self._next_delay)
        self._next_delay = 0.0


# ---------------------------------------------------------------------------
# fault injection
# ---------------------------------------------------------------------------


class FaultError(ConnectionError):
    """Raised by an armed `error` rule — a stand-in for the peer dying
    mid-call.  Subclasses ConnectionError so production retry/reconnect
    paths treat it exactly like a real network failure."""


class FaultRule:
    """One armed fault: fires at calls nth..nth+count-1 of `site`.

    kinds:
      error     fire() raises `exc` (default FaultError) — models a
                dropped connection / dead peer
      delay     fire() sleeps `delay_s` — models a stall
      truncate  mangle() returns a prefix of the data (`arg` bytes, or
                half the frame) — models a mid-write crash
      corrupt   mangle() flips bytes starting at offset `arg` (default
                middle) — models wire/disk corruption
    """

    KINDS = ("error", "delay", "truncate", "corrupt")

    def __init__(self, site: str, kind: str = "error", nth: int = 1,
                 count: int = 1, delay_s: float = 0.0,
                 exc: Optional[BaseException] = None,
                 arg: Optional[int] = None):
        if kind not in self.KINDS:
            raise ValueError(f"fault kind {kind!r}: expected {self.KINDS}")
        if nth < 1:
            raise ValueError(f"nth counts from 1, got {nth}")
        self.site = site
        self.kind = kind
        self.nth = nth
        self.count = count
        self.delay_s = delay_s
        self.exc = exc
        self.arg = arg
        self.fired = 0

    def _matches(self, site: str, call_no: int) -> bool:
        return (fnmatch.fnmatchcase(site, self.site)
                and self.nth <= call_no < self.nth + self.count)

    def __repr__(self):
        return (f"FaultRule({self.site!r}, {self.kind!r}, nth={self.nth}, "
                f"count={self.count}, fired={self.fired})")


class FaultInjector:
    """Process-local chaos hooks.  Disabled (zero-cost fast path) until a
    rule is armed via `inject()` or the ``PADDLE_TPU_FAULTS`` env var:

        PADDLE_TPU_FAULTS="master.connect:error:1,pserver.send:truncate:2"

    i.e. comma-separated ``site:kind[:nth[:count]]`` specs (site may be
    an fnmatch pattern).  Call counters are per site name and
    thread-safe."""

    def __init__(self):
        self._rules: List[FaultRule] = []
        self._calls: Dict[str, int] = {}
        self._lock = threading.Lock()
        # rate limiter for the fired-fault log line + eager flight
        # dump: a high-frequency rule (e.g. a per-decode-tick delay
        # simulating a slow accelerator) must not hose the disk or the
        # log — the metric counter still counts every firing
        self._last_note: Dict[tuple, float] = {}

    # -- configuration ------------------------------------------------------
    def inject(self, site: str, kind: str = "error", nth: int = 1,
               count: int = 1, delay_s: float = 0.0,
               exc: Optional[BaseException] = None,
               arg: Optional[int] = None) -> FaultRule:
        rule = FaultRule(site, kind, nth, count, delay_s, exc, arg)
        with self._lock:
            self._rules.append(rule)
        return rule

    def clear(self):
        """Drop all rules and reset call counters."""
        with self._lock:
            self._rules = []
            self._calls = {}
            self._last_note = {}

    def rules(self) -> List[FaultRule]:
        with self._lock:
            return list(self._rules)

    def load_env(self, spec: Optional[str] = None):
        """Arm rules from a ``PADDLE_TPU_FAULTS``-style spec string:
        comma-separated ``site:kind[:nth[:count[:arg]]]`` entries.  The
        trailing arg is the stall seconds for ``delay`` rules and the
        byte position/length for ``truncate``/``corrupt`` (a
        delay armed without seconds would be a silent no-op, so it is
        rejected)."""
        spec = spec if spec is not None else os.environ.get(
            "PADDLE_TPU_FAULTS", "")
        for part in filter(None, (p.strip() for p in spec.split(","))):
            fields = part.split(":")
            if len(fields) < 2:
                raise ValueError(
                    f"PADDLE_TPU_FAULTS entry {part!r}: expected "
                    "site:kind[:nth[:count[:arg]]]")
            site, kind = fields[0], fields[1]
            nth = int(fields[2]) if len(fields) > 2 else 1
            count = int(fields[3]) if len(fields) > 3 else 1
            arg = fields[4] if len(fields) > 4 else None
            if kind == "delay":
                if arg is None:
                    raise ValueError(
                        f"PADDLE_TPU_FAULTS entry {part!r}: delay needs "
                        "its seconds as the 5th field "
                        "(site:delay:nth:count:seconds)")
                self.inject(site, kind, nth=nth, count=count,
                            delay_s=float(arg))
            else:
                self.inject(site, kind, nth=nth, count=count,
                            arg=int(arg) if arg is not None else None)

    # -- hot-path hooks -----------------------------------------------------
    def _next_call(self, site: str) -> int:
        with self._lock:
            n = self._calls.get(site, 0) + 1
            self._calls[site] = n
            return n

    def _active_rule(self, site: str, kinds) -> Optional[FaultRule]:
        call_no = self._next_call(site)
        with self._lock:
            for rule in self._rules:
                if rule.kind in kinds and rule._matches(site, call_no):
                    rule.fired += 1
                    return rule
        return None

    def _note_fired(self, site: str, rule: FaultRule):
        _M_FAULTS.labels(site=site, kind=rule.kind).inc()
        now = time.monotonic()
        with self._lock:
            last = self._last_note.get((site, rule.kind), float("-inf"))
            if now - last < 1.0:
                return  # noted within the last second: count only
            self._last_note[(site, rule.kind)] = now
        _LOG.warning("fault injected at %s: kind=%s (rule %r)",
                     site, rule.kind, rule)
        try:
            # the moment a chaos fault fires is exactly the window a
            # post-mortem wants preserved — dump the flight ring now
            # (no-op when no recorder is armed).  The active trace id
            # rides along so the post-mortem joins the fault to the
            # request trace it poisoned (docs/resilience.md)
            from paddle_tpu.observability import flightrecorder, tracing
            flightrecorder.on_fault(site, rule.kind,
                                    trace_id=tracing.current_trace_id())
        except Exception as e:  # recorder trouble must not mask the
            # injected fault the caller is about to raise
            _LOG.debug("flight-recorder fault dump failed: %r", e)

    def fire(self, site: str):
        """Give error/delay rules a shot at this call site."""
        if not self._rules:
            return
        rule = self._active_rule(site, ("error", "delay"))
        if rule is None:
            return
        self._note_fired(site, rule)
        if rule.kind == "delay":
            time.sleep(rule.delay_s)
        else:
            raise rule.exc if rule.exc is not None else FaultError(
                f"fault injected at {site} "
                f"(call {self._calls.get(site)})")

    def mangle(self, site: str, data: bytes) -> bytes:
        """Give truncate/corrupt rules a shot at outgoing bytes; returns
        the (possibly modified) data.  Callers compare lengths/identity
        to decide whether to fail the connection afterwards."""
        if not self._rules:
            return data
        rule = self._active_rule(site, ("truncate", "corrupt"))
        if rule is None or not data:
            return data
        self._note_fired(site, rule)
        if rule.kind == "truncate":
            cut = rule.arg if rule.arg is not None else max(len(data) // 2, 1)
            return data[:min(cut, len(data) - 1)]
        off = rule.arg if rule.arg is not None else len(data) // 2
        off = min(off, len(data) - 1)
        return data[:off] + bytes([data[off] ^ 0xFF]) + data[off + 1:]


_INJECTOR: Optional[FaultInjector] = None
_INJECTOR_LOCK = threading.Lock()


def fault_injector() -> FaultInjector:
    """The process-wide injector (rules from PADDLE_TPU_FAULTS are armed
    on first access)."""
    global _INJECTOR
    if _INJECTOR is None:
        with _INJECTOR_LOCK:
            if _INJECTOR is None:
                inj = FaultInjector()
                inj.load_env()
                _INJECTOR = inj
    return _INJECTOR


def sched_fault_armed(name: str) -> bool:
    """Schedule-checker regression-pin hook: True only inside a test
    that reintroduces a historical race via
    analysis.schedcheck.arm_fault (docs/analysis.md "Schedule
    checking").  Guarded lazy import so runtime modules (pserver,
    serving) never pay for — or cycle on — the analysis package."""
    try:
        from ..analysis.schedcheck import fault_armed
    except Exception:
        return False
    return fault_armed(name)
