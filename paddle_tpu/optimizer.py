"""Optimizers: program rewrites appending backward + update ops.

Reference: /root/reference/python/paddle/v2/fluid/optimizer.py (`Optimizer`
base :29 — global LR var, per-param accumulators, `minimize` = append_backward
+ create_optimization_pass; SGD/Momentum/Adagrad/Adam/Adamax/DecayedAdagrad
subclasses).
"""
from __future__ import annotations

from typing import Dict

from .backward import append_backward
from .core.framework import (
    Variable,
    default_main_program,
    default_startup_program,
    unique_name,
)
from .regularizer import append_regularization_ops

__all__ = [
    "Optimizer",
    "SGD",
    "SGDOptimizer",
    "Momentum",
    "MomentumOptimizer",
    "Adagrad",
    "AdagradOptimizer",
    "Adam",
    "AdamOptimizer",
    "Adamax",
    "AdamaxOptimizer",
    "DecayedAdagrad",
    "DecayedAdagradOptimizer",
    "Adadelta",
    "AdadeltaOptimizer",
    "RMSProp",
    "RMSPropOptimizer",
    "Ftrl",
    "FtrlOptimizer",
    "ModelAverage",
]


class Optimizer:
    def __init__(self, learning_rate, regularization=None,
                 global_step=None):
        self._learning_rate = learning_rate
        self.regularization = regularization
        self._global_step = global_step
        self._accumulators: Dict[str, Dict[str, Variable]] = {}
        self._lr_var = None
        self._startup_program = None  # set by create_optimization_pass

    # -- learning rate -------------------------------------------------------
    def _create_lr_var(self, program):
        if isinstance(self._learning_rate, Variable):
            self._lr_var = self._learning_rate
            return
        name = unique_name("learning_rate")
        gb = program.global_block()
        self._lr_var = gb.create_var(
            name=name, shape=(1,), dtype="float32", persistable=True,
            stop_gradient=True)
        sb = (self._startup_program or
              default_startup_program()).global_block()
        sb.create_var(name=name, shape=(1,), dtype="float32",
                      persistable=True)
        sb.append_op("fill_constant", {}, {"Out": [name]},
                     {"shape": [1], "dtype": "float32",
                      "value": float(self._learning_rate)})

    def _lr_for(self, param):
        return self._lr_var

    # -- accumulators --------------------------------------------------------
    def _add_accumulator(self, name, param, fill_value=0.0, shape=None,
                         dtype=None):
        acc_name = f"{param.name}_{name}_acc"
        shape = list(shape if shape is not None else param.shape)
        dtype = dtype or param.dtype
        gb = param.block.program.global_block()
        acc = gb.create_var(name=acc_name, shape=shape, dtype=dtype,
                            persistable=True, stop_gradient=True)
        sb = (self._startup_program or
              default_startup_program()).global_block()
        sb.create_var(name=acc_name, shape=tuple(shape), dtype=dtype,
                      persistable=True)
        sb.append_op("fill_constant", {}, {"Out": [acc_name]},
                     {"shape": shape, "dtype": dtype,
                      "value": float(fill_value)})
        self._accumulators.setdefault(name, {})[param.name] = acc
        return acc

    def _get_accumulator(self, name, param):
        return self._accumulators[name][param.name]

    def _append_update_hooks(self, block, param):
        """Parameter update hooks (reference
        parameter/ParameterUpdaterHook.cpp, attached via ParameterConfig
        update_hooks).  'pruning': a static mask from the initial weight
        magnitudes re-applied after every optimizer step."""
        for hook in getattr(param, "update_hooks", None) or ():
            if hook.get("type") != "pruning":
                raise ValueError(f"unknown update hook {hook!r}")
            mask_name = f"{param.name}_prune_mask"
            gb = block.program.global_block()
            if not gb.has_var(mask_name):
                gb.create_var(name=mask_name, shape=list(param.shape),
                              dtype=param.dtype, persistable=True,
                              stop_gradient=True)
                sb = (self._startup_program or
                      default_startup_program()).global_block()
                sb.create_var(name=mask_name, shape=list(param.shape),
                              dtype=param.dtype, persistable=True)
                sb.append_op("pruning_mask", {"Param": [param.name]},
                             {"Mask": [mask_name]},
                             {"sparsity_ratio":
                              float(hook.get("sparsity_ratio", 0.6))})
                # static pruning starts from a pruned net
                sb.append_op("elementwise_mul",
                             {"X": [param.name], "Y": [mask_name]},
                             {"Out": [param.name]}, {"axis": -1})
            block.append_op("elementwise_mul",
                            {"X": [param.name], "Y": [mask_name]},
                            {"Out": [param.name]}, {"axis": -1})

    # -- hooks ---------------------------------------------------------------
    def _create_accumulators(self, block, parameters):
        pass

    def _append_optimize_op(self, block, param_and_grad):
        raise NotImplementedError

    def _finish_update(self, block):
        pass

    # -- main entry ----------------------------------------------------------
    def create_optimization_pass(self, params_grads, loss,
                                 startup_program=None):
        if not params_grads:
            return []
        block = loss.block
        program = block.program
        # init ops (LR, accumulators) go into the caller's startup program —
        # falling back to the ambient default only when none was given
        # (reference optimizer.py threads startup_program the same way)
        self._startup_program = startup_program
        self._create_lr_var(program)
        self._create_accumulators(block, [p for p, _ in params_grads])
        start = len(block.ops)
        for p, g in params_grads:
            if g is None:
                continue
            self._append_optimize_op(block, (p, g))
            self._append_update_hooks(block, p)
        self._finish_update(block)
        if self._global_step is not None:
            block.append_op("increment",
                            {"X": [self._global_step.name]},
                            {"Out": [self._global_step.name]},
                            {"step": 1.0})
        # the ops this pass appended — what DistributeTranspiler moves to
        # the pserver program (reference optimizer.py returns them too)
        return block.ops[start:]

    def minimize(self, loss, startup_program=None, parameter_list=None,
                 no_grad_set=None):
        params_grads = append_backward(loss, parameter_list, no_grad_set)
        # gradient clipping between backward and regularization, matching
        # reference optimizer.py minimize ordering (clip.py:236)
        from .clip import append_gradient_clip_ops

        params_grads = append_gradient_clip_ops(params_grads)
        params_grads = append_regularization_ops(params_grads,
                                                 self.regularization)
        optimize_ops = self.create_optimization_pass(params_grads, loss,
                                                     startup_program)
        return optimize_ops, params_grads


class SGDOptimizer(Optimizer):
    def _append_optimize_op(self, block, pg):
        p, g = pg
        block.append_op(
            "sgd",
            {"Param": [p.name], "Grad": [g.name],
             "LearningRate": [self._lr_for(p).name]},
            {"ParamOut": [p.name]})


class MomentumOptimizer(Optimizer):
    def __init__(self, learning_rate, momentum, use_nesterov=False, **kw):
        super().__init__(learning_rate, **kw)
        self._momentum = momentum
        self._use_nesterov = use_nesterov

    def _create_accumulators(self, block, parameters):
        for p in parameters:
            self._add_accumulator("velocity", p)

    def _append_optimize_op(self, block, pg):
        p, g = pg
        v = self._get_accumulator("velocity", p)
        block.append_op(
            "momentum",
            {"Param": [p.name], "Grad": [g.name], "Velocity": [v.name],
             "LearningRate": [self._lr_for(p).name]},
            {"ParamOut": [p.name], "VelocityOut": [v.name]},
            {"mu": self._momentum, "use_nesterov": self._use_nesterov})


class AdagradOptimizer(Optimizer):
    def __init__(self, learning_rate, epsilon=1e-6, **kw):
        super().__init__(learning_rate, **kw)
        self._epsilon = epsilon

    def _create_accumulators(self, block, parameters):
        for p in parameters:
            self._add_accumulator("moment", p)

    def _append_optimize_op(self, block, pg):
        p, g = pg
        m = self._get_accumulator("moment", p)
        block.append_op(
            "adagrad",
            {"Param": [p.name], "Grad": [g.name], "Moment": [m.name],
             "LearningRate": [self._lr_for(p).name]},
            {"ParamOut": [p.name], "MomentOut": [m.name]},
            {"epsilon": self._epsilon})


class AdamOptimizer(Optimizer):
    def __init__(self, learning_rate=0.001, beta1=0.9, beta2=0.999,
                 epsilon=1e-8, **kw):
        super().__init__(learning_rate, **kw)
        self._beta1, self._beta2, self._epsilon = beta1, beta2, epsilon

    def _create_accumulators(self, block, parameters):
        for p in parameters:
            self._add_accumulator("moment1", p)
            self._add_accumulator("moment2", p)
        # global beta powers (reference optimizer.py AdamOptimizer).
        # ALWAYS f32: with bf16 params, a bf16 beta2_pow rounds 0.999 to
        # 1.0 and stays there (the scale-op update is outside the f32
        # optimizer-arithmetic wrapper), making lr_t exactly 0.
        self._beta1_pow = self._add_accumulator(
            "beta1_pow", parameters[0], fill_value=self._beta1, shape=[1],
            dtype="float32")
        self._beta2_pow = self._add_accumulator(
            "beta2_pow", parameters[0], fill_value=self._beta2, shape=[1],
            dtype="float32")

    def _append_optimize_op(self, block, pg):
        p, g = pg
        m1 = self._get_accumulator("moment1", p)
        m2 = self._get_accumulator("moment2", p)
        block.append_op(
            "adam",
            {"Param": [p.name], "Grad": [g.name], "Moment1": [m1.name],
             "Moment2": [m2.name],
             "LearningRate": [self._lr_for(p).name],
             "Beta1Pow": [self._beta1_pow.name],
             "Beta2Pow": [self._beta2_pow.name]},
            {"ParamOut": [p.name], "Moment1Out": [m1.name],
             "Moment2Out": [m2.name]},
            {"beta1": self._beta1, "beta2": self._beta2,
             "epsilon": self._epsilon})

    def _finish_update(self, block):
        block.append_op("scale", {"X": [self._beta1_pow.name]},
                        {"Out": [self._beta1_pow.name]},
                        {"scale": self._beta1})
        block.append_op("scale", {"X": [self._beta2_pow.name]},
                        {"Out": [self._beta2_pow.name]},
                        {"scale": self._beta2})


class AdamaxOptimizer(Optimizer):
    def __init__(self, learning_rate=0.001, beta1=0.9, beta2=0.999,
                 epsilon=1e-8, **kw):
        super().__init__(learning_rate, **kw)
        self._beta1, self._beta2, self._epsilon = beta1, beta2, epsilon

    def _create_accumulators(self, block, parameters):
        for p in parameters:
            self._add_accumulator("moment", p)
            self._add_accumulator("inf_norm", p)
        # f32 for the same reason as Adam's beta pows
        self._beta1_pow = self._add_accumulator(
            "beta1_pow", parameters[0], fill_value=self._beta1, shape=[1],
            dtype="float32")

    def _append_optimize_op(self, block, pg):
        p, g = pg
        m = self._get_accumulator("moment", p)
        inf = self._get_accumulator("inf_norm", p)
        block.append_op(
            "adamax",
            {"Param": [p.name], "Grad": [g.name], "Moment": [m.name],
             "InfNorm": [inf.name],
             "LearningRate": [self._lr_for(p).name],
             "Beta1Pow": [self._beta1_pow.name]},
            {"ParamOut": [p.name], "MomentOut": [m.name],
             "InfNormOut": [inf.name]},
            {"beta1": self._beta1, "beta2": self._beta2,
             "epsilon": self._epsilon})

    def _finish_update(self, block):
        block.append_op("scale", {"X": [self._beta1_pow.name]},
                        {"Out": [self._beta1_pow.name]},
                        {"scale": self._beta1})


class DecayedAdagradOptimizer(Optimizer):
    def __init__(self, learning_rate, decay=0.95, epsilon=1e-6, **kw):
        super().__init__(learning_rate, **kw)
        self._decay, self._epsilon = decay, epsilon

    def _create_accumulators(self, block, parameters):
        for p in parameters:
            self._add_accumulator("moment", p)

    def _append_optimize_op(self, block, pg):
        p, g = pg
        m = self._get_accumulator("moment", p)
        block.append_op(
            "decayed_adagrad",
            {"Param": [p.name], "Grad": [g.name], "Moment": [m.name],
             "LearningRate": [self._lr_for(p).name]},
            {"ParamOut": [p.name], "MomentOut": [m.name]},
            {"decay": self._decay, "epsilon": self._epsilon})


class AdadeltaOptimizer(Optimizer):
    def __init__(self, learning_rate=1.0, rho=0.95, epsilon=1e-6, **kw):
        super().__init__(learning_rate, **kw)
        self._rho, self._epsilon = rho, epsilon

    def _create_accumulators(self, block, parameters):
        for p in parameters:
            self._add_accumulator("avg_squared_grad", p)
            self._add_accumulator("avg_squared_update", p)

    def _append_optimize_op(self, block, pg):
        p, g = pg
        asg = self._get_accumulator("avg_squared_grad", p)
        asu = self._get_accumulator("avg_squared_update", p)
        block.append_op(
            "adadelta",
            {"Param": [p.name], "Grad": [g.name],
             "AvgSquaredGrad": [asg.name],
             "AvgSquaredUpdate": [asu.name]},
            {"ParamOut": [p.name], "AvgSquaredGradOut": [asg.name],
             "AvgSquaredUpdateOut": [asu.name]},
            {"rho": self._rho, "epsilon": self._epsilon})


class RMSPropOptimizer(Optimizer):
    def __init__(self, learning_rate, rho=0.95, epsilon=1e-6,
                 momentum=0.0, **kw):
        super().__init__(learning_rate, **kw)
        self._rho, self._epsilon, self._momentum = rho, epsilon, momentum

    def _create_accumulators(self, block, parameters):
        for p in parameters:
            self._add_accumulator("mean_square", p)
            self._add_accumulator("momentum", p)

    def _append_optimize_op(self, block, pg):
        p, g = pg
        ms = self._get_accumulator("mean_square", p)
        mom = self._get_accumulator("momentum", p)
        block.append_op(
            "rmsprop",
            {"Param": [p.name], "Grad": [g.name],
             "MeanSquare": [ms.name], "Moment": [mom.name],
             "LearningRate": [self._lr_for(p).name]},
            {"ParamOut": [p.name], "MeanSquareOut": [ms.name],
             "MomentOut": [mom.name]},
            {"decay": self._rho, "epsilon": self._epsilon,
             "momentum": self._momentum})


class FtrlOptimizer(Optimizer):
    def __init__(self, learning_rate, l1=0.0, l2=0.0, lr_power=-0.5, **kw):
        super().__init__(learning_rate, **kw)
        self._l1, self._l2, self._lr_power = l1, l2, lr_power

    def _create_accumulators(self, block, parameters):
        for p in parameters:
            self._add_accumulator("squared", p)
            self._add_accumulator("linear", p)

    def _append_optimize_op(self, block, pg):
        p, g = pg
        sq = self._get_accumulator("squared", p)
        lin = self._get_accumulator("linear", p)
        block.append_op(
            "ftrl",
            {"Param": [p.name], "SquaredAccumulator": [sq.name],
             "LinearAccumulator": [lin.name], "Grad": [g.name],
             "LearningRate": [self._lr_for(p).name]},
            {"ParamOut": [p.name], "SquaredAccumOut": [sq.name],
             "LinearAccumOut": [lin.name]},
            {"l1": self._l1, "l2": self._l2, "lr_power": self._lr_power})


class ModelAverage(Optimizer):
    """Polyak/windowed parameter averaging for evaluation.

    Reference: paddle/parameter/AverageOptimizer.cpp (legacy
    `AverageOptimizer`/`AverageSparseOptimizer`, enabled by the
    `average_window` setting in trainer configs).  Appends one
    `average_accumulates` op per parameter to the main program (run after
    the optimizer update ops), then `apply()` temporarily swaps parameters
    for their window average and `restore()` puts the trained values back.

        model_average = fluid.optimizer.ModelAverage(0.15)
        ... train ...
        with model_average.apply(exe):
            evaluate()
    """

    def __init__(self, average_window_rate=0.15, min_average_window=10000,
                 max_average_window=10000, program=None,
                 startup_program=None, **kw):
        super().__init__(0.0, **kw)
        from .core.framework import Parameter, default_main_program

        self._avg_window = float(average_window_rate)
        self._min_window = int(min_average_window)
        self._max_window = int(max_average_window)
        program = program or default_main_program()
        self._program = program
        self._startup_program = startup_program
        gb = program.global_block()
        self._params = sorted(
            (v for v in gb.vars.values() if isinstance(v, Parameter)
             and getattr(v, "do_model_average", None) is not False),
            key=lambda v: v.name)
        self._restore_backup = None
        for p in self._params:
            self._add_accumulator("sum_1", p, dtype="float32")
            self._add_accumulator("sum_2", p, dtype="float32")
            self._add_accumulator("sum_3", p, dtype="float32")
            for cname in ("num_accumulates", "old_num_accumulates",
                          "num_updates"):
                self._add_accumulator(cname, p, shape=[1], dtype="int32")
            gb.append_op(
                "average_accumulates",
                {"Param": [p.name],
                 "InSum1": [self._get_accumulator("sum_1", p).name],
                 "InSum2": [self._get_accumulator("sum_2", p).name],
                 "InSum3": [self._get_accumulator("sum_3", p).name],
                 "InNumAccumulates":
                     [self._get_accumulator("num_accumulates", p).name],
                 "InOldNumAccumulates":
                     [self._get_accumulator("old_num_accumulates", p).name],
                 "InNumUpdates":
                     [self._get_accumulator("num_updates", p).name]},
                {"OutSum1": [self._get_accumulator("sum_1", p).name],
                 "OutSum2": [self._get_accumulator("sum_2", p).name],
                 "OutSum3": [self._get_accumulator("sum_3", p).name],
                 "OutNumAccumulates":
                     [self._get_accumulator("num_accumulates", p).name],
                 "OutOldNumAccumulates":
                     [self._get_accumulator("old_num_accumulates", p).name],
                 "OutNumUpdates":
                     [self._get_accumulator("num_updates", p).name]},
                {"average_window": self._avg_window,
                 "min_average_window": self._min_window,
                 "max_average_window": self._max_window})
        program.bump_version()

    def _averaged_value(self, p, scope):
        import numpy as np

        s = sum(np.asarray(
            scope.find_var(self._get_accumulator(n, p).name),
            dtype=np.float64) for n in ("sum_1", "sum_2", "sum_3"))
        cnt = sum(int(np.asarray(
            scope.find_var(self._get_accumulator(n, p).name)).reshape(()))
            for n in ("num_accumulates", "old_num_accumulates"))
        if cnt == 0:
            return None
        return (s / cnt).astype(p.dtype)

    def apply(self, executor=None, need_restore=True, scope=None):
        """Context manager: params <- window average inside, original
        values back on exit (when need_restore)."""
        import contextlib

        import numpy as np

        from .core.executor import global_scope

        scope = scope or global_scope()

        @contextlib.contextmanager
        def _ctx():
            backup = {}
            for p in self._params:
                avg = self._averaged_value(p, scope)
                if avg is None:
                    continue
                backup[p.name] = np.asarray(scope.find_var(p.name)).copy()
                scope.set_var(p.name, avg)
            self._restore_backup = backup
            try:
                yield
            finally:
                if need_restore:
                    self.restore(executor, scope=scope)

        return _ctx()

    def restore(self, executor=None, scope=None):
        from .core.executor import global_scope

        scope = scope or global_scope()
        for name, value in (self._restore_backup or {}).items():
            scope.set_var(name, value)
        self._restore_backup = None


SGD = SGDOptimizer
Momentum = MomentumOptimizer
Adagrad = AdagradOptimizer
Adam = AdamOptimizer
Adamax = AdamaxOptimizer
DecayedAdagrad = DecayedAdagradOptimizer
Adadelta = AdadeltaOptimizer
RMSProp = RMSPropOptimizer
Ftrl = FtrlOptimizer
