"""Composed network patterns.

Reference: /root/reference/python/paddle/v2/fluid/nets.py:1-339
(simple_img_conv_pool, img_conv_group, sequence_conv_pool, glu,
scaled_dot_product_attention).
"""
from __future__ import annotations

from . import layers

__all__ = [
    "simple_img_conv_pool",
    "img_conv_group",
    "glu",
    "scaled_dot_product_attention",
    "sequence_conv_pool",
]


def simple_img_conv_pool(input, num_filters, filter_size, pool_size,
                         pool_stride, act, param_attr=None,
                         pool_type="max"):
    conv_out = layers.conv2d(input=input, num_filters=num_filters,
                             filter_size=filter_size, param_attr=param_attr,
                             act=act)
    return layers.pool2d(input=conv_out, pool_size=pool_size,
                         pool_type=pool_type, pool_stride=pool_stride)


def img_conv_group(input, conv_num_filter, pool_size, conv_padding=1,
                   conv_filter_size=3, conv_act=None, param_attr=None,
                   conv_with_batchnorm=False, conv_batchnorm_drop_rate=0.0,
                   pool_stride=1, pool_type="max"):
    tmp = input
    if isinstance(conv_padding, int):
        conv_padding = [conv_padding] * len(conv_num_filter)
    if isinstance(conv_filter_size, int):
        conv_filter_size = [conv_filter_size] * len(conv_num_filter)
    if isinstance(conv_with_batchnorm, bool):
        conv_with_batchnorm = [conv_with_batchnorm] * len(conv_num_filter)
    if isinstance(conv_batchnorm_drop_rate, (float, int)):
        conv_batchnorm_drop_rate = ([conv_batchnorm_drop_rate]
                                    * len(conv_num_filter))
    for i, nf in enumerate(conv_num_filter):
        local_conv_act = None if conv_with_batchnorm[i] else conv_act
        tmp = layers.conv2d(input=tmp, num_filters=nf,
                            filter_size=conv_filter_size[i],
                            padding=conv_padding[i],
                            param_attr=param_attr, act=local_conv_act)
        if conv_with_batchnorm[i]:
            tmp = layers.batch_norm(input=tmp, act=conv_act)
            if conv_batchnorm_drop_rate[i] > 0:
                tmp = layers.dropout(x=tmp,
                                     dropout_prob=conv_batchnorm_drop_rate[i])
    return layers.pool2d(input=tmp, pool_size=pool_size,
                         pool_type=pool_type, pool_stride=pool_stride)


def glu(input, dim=-1):
    """Gated linear unit: split + sigmoid gate (reference nets.py glu)."""
    a, b = layers.split(input, num_or_sections=2, dim=dim)
    gate = layers.sigmoid(b)
    return layers.elementwise_mul(a, gate)


def scaled_dot_product_attention(queries, keys, values,
                                 num_heads=1, dropout_rate=0.0,
                                 causal=False, is_test=False):
    """Multi-head attention on [batch, seq, dim] tensors (reference
    nets.py:162-219).  With no attention-weight dropout the hot path lowers
    to the Pallas flash-attention kernel; with dropout it falls back to the
    reference's matmul -> softmax -> dropout -> matmul composition.
    `causal=True` masks future positions (decoder self-attention)."""
    import math

    import numpy as np

    d_model = int(queries.shape[-1])
    if num_heads < 1:
        raise ValueError("num_heads must be >= 1")
    if d_model % num_heads:
        raise ValueError(
            f"hidden size {d_model} not divisible by num_heads {num_heads}")
    d_head = d_model // num_heads

    def split_heads(x):
        # [b, s, d] -> [b, s, h, d/h]
        return layers.reshape(x, shape=[0, 0, num_heads, d_head])

    if not dropout_rate or is_test:
        # at inference dropout is a no-op, so the fused kernel stays exact
        out = layers.flash_attention(split_heads(queries),
                                     split_heads(keys),
                                     split_heads(values), causal=causal)
        return layers.reshape(out, shape=[0, 0, d_model])

    # composed fallback (weight dropout needs the materialized weights)
    q = layers.transpose(split_heads(queries), axis=[0, 2, 1, 3])
    k = layers.transpose(split_heads(keys), axis=[0, 2, 1, 3])
    v = layers.transpose(split_heads(values), axis=[0, 2, 1, 3])
    scaled_q = layers.scale(q, scale=1.0 / math.sqrt(d_head))
    product = layers.matmul(scaled_q, k, transpose_y=True)
    if causal:
        seq_q = int(queries.shape[1])
        seq_k = int(keys.shape[1])
        mask = np.triu(np.full((seq_q, seq_k), -1e9, dtype=np.float32), k=1)
        product = layers.elementwise_add(product, layers.assign(mask),
                                         axis=2)
    weights = layers.softmax(product)
    weights = layers.dropout(weights, dropout_prob=dropout_rate,
                             is_test=is_test)
    ctx = layers.matmul(weights, v)                  # [b, h, s, d/h]
    ctx = layers.transpose(ctx, axis=[0, 2, 1, 3])   # [b, s, h, d/h]
    return layers.reshape(ctx, shape=[0, 0, d_model])


def sequence_conv_pool(input, num_filters, filter_size, param_attr=None,
                       act="sigmoid", pool_type="max"):
    conv_out = layers.sequence_conv(input=input, num_filters=num_filters,
                                    filter_size=filter_size,
                                    param_attr=param_attr, act=act)
    return layers.sequence_pool(input=conv_out, pool_type=pool_type)
